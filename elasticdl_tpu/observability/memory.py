"""Deep profiling plane, part 2: live/peak memory accounting.

A MemoryAccountant samples, on a background thread (or on demand from
tests and the report tools):

- device memory: the sum of `jax.live_arrays()` byte sizes (works on
  every backend, CPU included) and, where the runtime reports them,
  `device.memory_stats()` bytes_in_use / peak_bytes_in_use per device;
- host memory: VmRSS from /proc/self/status (live) and
  `resource.getrusage` ru_maxrss (peak) — the PS-side number, since PS
  shards are pure-host processes whose embedding slabs dominate RSS;
- registered components: any subsystem can `add_provider(fn)` returning
  {component: bytes} — the PS registers per-embedding-table and dense-
  param byte counts so a hot shard's footprint is attributable to the
  table that causes it.

Exported as `edl_mem_*` gauges; a `mem_high_watermark` event fires when
a sample's live device total jumps past the previous peak by the
ELASTICDL_MEM_WATERMARK_RATIO factor — that is the "which step blew up
HBM" breadcrumb, timestamped into the same events.jsonl the elastic
timeline lives in. Sampling period: ELASTICDL_MEM_SAMPLE_SECONDS (0
disables the thread; direct `sample()` calls always work).

Everything degrades to absent gauges, never to a training failure: no
jax, no /proc, no providers — each leg is independently guarded.
"""

import os
import threading

from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import events as _events
from elasticdl_tpu.observability.metrics import default_registry

logger = get_logger("observability.memory")

SAMPLE_SECONDS_ENV = "ELASTICDL_MEM_SAMPLE_SECONDS"
WATERMARK_RATIO_ENV = "ELASTICDL_MEM_WATERMARK_RATIO"

_REG = default_registry()
_G_DEVICE_LIVE = _REG.gauge(
    "edl_mem_device_live_bytes",
    "Bytes held by live jax arrays at the last sample",
)
_G_DEVICE_PEAK = _REG.gauge(
    "edl_mem_device_peak_bytes",
    "Peak live-array bytes observed by any sample this process",
)
_G_DEVICE_STATS = _REG.gauge(
    "edl_mem_device_stats_bytes",
    "Runtime-reported device memory (platforms with memory_stats)",
    labelnames=("device", "stat"),
)
_G_HOST_RSS = _REG.gauge(
    "edl_mem_host_rss_bytes",
    "Resident set size of this process at the last sample",
)
_G_HOST_PEAK = _REG.gauge(
    "edl_mem_host_peak_rss_bytes",
    "Peak resident set size (getrusage high watermark)",
)
_G_COMPONENT = _REG.gauge(
    "edl_mem_component_bytes",
    "Registered component byte counts (PS embedding tables, dense "
    "params, ...)",
    labelnames=("component",),
)


def host_rss_bytes():
    """Current VmRSS from /proc (Linux); None elsewhere."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return None


def host_peak_rss_bytes():
    try:
        import resource

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(peak) * 1024  # Linux reports KiB
    except Exception:
        return None


def device_live_bytes():
    """Sum of live jax array bytes; None when jax is absent/unloaded.
    Only counts arrays already materialized — cheap relative to any
    actual training step."""
    import sys

    if "jax" not in sys.modules:
        return None  # never force the jax import from a sampler thread
    try:
        import jax

        return sum(
            int(getattr(a, "nbytes", 0)) for a in jax.live_arrays()
        )
    except Exception:
        return None


def device_memory_stats():
    """{device_label: {stat: bytes}} from backends that report them
    (TPU/GPU); {} on CPU."""
    import sys

    if "jax" not in sys.modules:
        return {}
    out = {}
    try:
        import jax

        for d in jax.local_devices():
            stats = d.memory_stats()
            if not stats:
                continue
            picked = {
                k: v
                for k, v in stats.items()
                if k in ("bytes_in_use", "peak_bytes_in_use",
                         "bytes_limit")
            }
            if picked:
                out[f"{d.platform}:{d.id}"] = picked
    except Exception:
        return {}
    return out


class MemoryAccountant:
    """Samples process memory into gauges + high-watermark events."""

    def __init__(self, watermark_ratio=None):
        if watermark_ratio is None:
            watermark_ratio = knobs.get_float(WATERMARK_RATIO_ENV)
        self.watermark_ratio = max(1.0, watermark_ratio)
        self._lock = threading.Lock()
        self._providers = []
        self._device_peak = 0
        self._stop = threading.Event()
        self._thread = None

    def add_provider(self, fn):
        """Register a callable() -> {component: bytes}; exceptions and
        non-dict returns are swallowed per sample."""
        with self._lock:
            if fn not in self._providers:
                self._providers.append(fn)

    def remove_provider(self, fn):
        with self._lock:
            if fn in self._providers:
                self._providers.remove(fn)

    # ---------- sampling ----------

    def sample(self):
        """One pass; returns the sample dict (tests and /api consumers).
        Also the thread body's unit of work."""
        out = {}
        live = device_live_bytes()
        if live is not None:
            out["device_live_bytes"] = live
            _G_DEVICE_LIVE.set(live)
            # Peak decision, gauge, and event all under the lock:
            # sample() is documented as callable concurrently with the
            # sampler thread, and an unlocked late writer could pin the
            # peak gauge below the true peak (or double-fire the event).
            with self._lock:
                prev_peak = self._device_peak
                if live > prev_peak:
                    self._device_peak = live
                    _G_DEVICE_PEAK.set(live)
                    if (
                        prev_peak > 0
                        and live > prev_peak * self.watermark_ratio
                    ):
                        _events.emit(
                            "mem_high_watermark",
                            bytes=live,
                            previous_peak=prev_peak,
                            ratio=round(live / prev_peak, 3),
                        )
        stats = device_memory_stats()
        if stats:
            out["device_stats"] = stats
            for device, picked in stats.items():
                for stat, value in picked.items():
                    _G_DEVICE_STATS.labels(
                        device=device, stat=stat
                    ).set(value)
        rss = host_rss_bytes()
        if rss is not None:
            out["host_rss_bytes"] = rss
            _G_HOST_RSS.set(rss)
        peak = host_peak_rss_bytes()
        if peak is not None:
            out["host_peak_rss_bytes"] = peak
            _G_HOST_PEAK.set(peak)
        with self._lock:
            providers = list(self._providers)
        components = {}
        for fn in providers:
            try:
                result = fn()
            except Exception:
                continue
            if not isinstance(result, dict):
                continue
            for component, value in result.items():
                components[str(component)] = int(value)
        for component, value in components.items():
            _G_COMPONENT.labels(component=component).set(value)
        if components:
            out["components"] = components
        return out

    @property
    def device_peak_bytes(self):
        with self._lock:
            return self._device_peak

    # ---------- lifecycle ----------

    def start(self, interval=None):
        if interval is None:
            interval = knobs.get_float(SAMPLE_SECONDS_ENV)
        if interval <= 0 or self._thread is not None:
            return self
        # A close()d accountant must be restartable: setup()/close()
        # cycles reuse the process-global instance, and a stale stop
        # flag would kill the relaunched thread after zero samples.
        self._stop.clear()
        self._interval = interval

        def run():
            while not self._stop.is_set():
                try:
                    self.sample()
                except Exception:
                    logger.warning("memory sample failed", exc_info=True)
                self._stop.wait(self._interval)

        self._thread = threading.Thread(
            target=run, name="edl-mem-accountant", daemon=True
        )
        self._thread.start()
        return self

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


_accountant = None
_accountant_lock = threading.Lock()


def accountant():
    """The process-global accountant (created on first use; providers
    can register before the sampler thread ever starts)."""
    global _accountant
    with _accountant_lock:
        if _accountant is None:
            _accountant = MemoryAccountant()
        return _accountant


def embedding_bytes_provider(parameters):
    """Provider for a PS shard's ps.Parameters: per-table used-row bytes
    plus the dense-parameter total — `os.environ`-free, lock-free reads
    of sizes that only grow."""

    def provider():
        out = {}
        dense = 0
        for arr in parameters.dense.values():
            dense += int(getattr(arr, "nbytes", 0))
        if dense:
            out["ps_dense_params"] = dense
        for name, table in parameters.embedding_tables.items():
            rows = len(table)
            itemsize = getattr(table, "dtype", None)
            itemsize = getattr(itemsize, "itemsize", 4) or 4
            out[f"ps_embedding:{name}"] = rows * table.dim * itemsize
        return out

    return provider
