"""Job-level telemetry aggregation: the master watches *performance*.

PR 1 gave every process its own /metrics endpoint; this module closes the
loop. A TelemetryAggregator thread runs inside the master, discovers every
per-role endpoint from `<obs_dir>/endpoints/*.json`, scrapes each /metrics
on an interval, parses the exposition text back into samples
(promtext.py), and keeps a bounded ring-buffer time-series store per
(role, metric, labels). From the store it derives job-level signals:

  records/s throughput (+ a short history for sparklines)
  per-worker step-time mean/p50/p99/EWMA from the phase histograms
  straggler scores (per-worker step latency vs. the fleet median)
  PS shard push/pull byte rates and load-imbalance scores
  task-queue drain rate and completion ETA
  per-worker MFU (when the worker publishes its estimate)

The signals are re-exported on the master's own registry as `edl_job_*`
gauges (so one scrape of the master answers "who is slow" without fanning
out), fed through the alert rules engine (alerts.py), and published as a
JSON dict behind the exporter's /api/summary — the feed for `edl dash`.

Scrape failures are expected steady-state noise (processes relaunch,
endpoints rewrite) and only count `edl_job_scrape_errors_total`.
"""

import collections
import json
import math
import os
import statistics
import threading
import time
import urllib.request

from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import alerts as alerts_mod
from elasticdl_tpu.observability import promtext
from elasticdl_tpu.observability import push as push_mod
from elasticdl_tpu.observability.metrics import default_registry

logger = get_logger("observability.aggregator")

INTERVAL_ENV = "ELASTICDL_AGGREGATOR_INTERVAL"

# Ring depth per series: at the default 2 s interval this is ~8.5 min of
# history — enough for rate windows and dashboard sparklines, bounded
# regardless of job length.
SERIES_DEPTH = 256

# Throughput/step-time rates are computed over a sliding window of this
# many seconds (at least two scrapes apart).
RATE_WINDOW_S = 20.0

_EWMA_ALPHA = 0.3

# Minimum windowed step-count before a worker's latency participates in
# straggler scoring: one slow compile must not flag a healthy worker.
MIN_STEP_SAMPLES = 3

STALE_SCRAPES_ENV = "ELASTICDL_ENDPOINT_STALE_SCRAPES"


def read_endpoints(endpoints_dir):
    """Parse every advertisement under one endpoints/ dir (shared with
    the master's StartProfile fan-out)."""
    endpoints = []
    try:
        entries = os.listdir(endpoints_dir)
    except OSError:
        return endpoints
    for entry in sorted(entries):
        if not entry.endswith(".json"):
            continue
        try:
            with open(os.path.join(endpoints_dir, entry)) as f:
                info = json.load(f)
        except (OSError, ValueError):
            continue  # mid-rewrite; next pass sees it whole
        if info.get("port"):
            endpoints.append(info)
    return endpoints


def _snap_field(snap, name, default):
    """Field access across pb.TelemetrySnapshot / dict / namespace —
    ingest_push accepts all three (tests and relays skip the proto)."""
    if isinstance(snap, dict):
        return snap.get(name, default)
    return getattr(snap, name, default)


class SeriesStore:
    """Bounded (role, metric, labels) -> deque[(ts, value)] store."""

    def __init__(self, depth=SERIES_DEPTH):
        self._depth = depth
        self._series = {}

    def add(self, role, name, labels, value, ts):
        key = (role, name, tuple(sorted(labels)))
        series = self._series.get(key)
        if series is None:
            series = collections.deque(maxlen=self._depth)
            self._series[key] = series
        series.append((ts, value))

    def latest(self, role, name, labels=()):
        series = self._series.get((role, name, tuple(sorted(labels))))
        return series[-1][1] if series else None

    def rate(self, role, name, labels=(), window_s=RATE_WINDOW_S,
             now=None):
        """(newest - oldest-within-window) / dt, or None with < 2 points.
        Counter resets (process relaunch) clamp to None for the window.
        With `now`, a series whose newest point is older than the window
        is STALE (the process stopped reporting) and answers None — a
        dead worker's last numbers must age out, not freeze."""
        series = self._series.get((role, name, tuple(sorted(labels))))
        if not series or len(series) < 2:
            return None
        t_new, v_new = series[-1]
        if now is not None and t_new < now - window_s:
            return None
        # The loop always binds: series[-1] itself satisfies the cutoff.
        t_old = v_old = None
        for ts, value in series:
            if ts >= t_new - window_s:
                t_old, v_old = ts, value
                break
        if t_old is None or t_new <= t_old:
            return None
        if v_new < v_old:
            return None  # reset mid-window
        return (v_new - v_old) / (t_new - t_old)

    def delta(self, role, name, labels=(), window_s=RATE_WINDOW_S,
              now=None):
        series = self._series.get((role, name, tuple(sorted(labels))))
        if not series or len(series) < 2:
            return None
        t_new, v_new = series[-1]
        if now is not None and t_new < now - window_s:
            return None  # stale series (see rate())
        v_old = None
        for ts, value in series:
            if ts >= t_new - window_s:
                v_old = value
                break
        if v_old is None or v_new < v_old:
            return None
        return v_new - v_old

    def roles(self):
        return sorted({role for role, _, _ in self._series})

    def labelsets(self, role, name):
        """Label tuples of every stored series of one (role, family) —
        the query surface for family-wide sums (keeps callers off the
        internal key layout)."""
        return [
            labels
            for (s_role, s_name, labels) in list(self._series)
            if s_role == role and s_name == name
        ]


def percentile(sorted_values, q):
    """Nearest-rank percentile of an ascending list; None when empty.
    (The histogram-bucket estimator above is for cumulative buckets;
    this one is for plain value lists — fleet rollups.)"""
    if not sorted_values:
        return None
    rank = math.ceil(q * len(sorted_values)) - 1
    return sorted_values[min(len(sorted_values) - 1, max(0, rank))]


def skew_scores(values, min_subjects=2):
    """{subject: value} -> {subject: value / fleet median}; empty when
    fewer than min_subjects report or the median is degenerate. The
    straggler and PS-imbalance signals are both this shape.

    median_low, not median: with an even fleet the interpolating median
    averages the two middle values, so in the smallest elastic world (2
    workers) one straggler drags the baseline up with it and its score
    asymptotes to 2.0 — the default threshold would be unreachable
    exactly where the drill runs. The low median keeps the baseline on a
    healthy member."""
    vals = {
        k: v
        for k, v in values.items()
        if v is not None and v > 0 and math.isfinite(v)
    }
    if len(vals) < min_subjects:
        return {}
    median = statistics.median_low(sorted(vals.values()))
    if median <= 0:
        return {}
    return {k: v / median for k, v in vals.items()}


def histogram_quantile(bounds_counts, q):
    """Estimate a quantile from cumulative histogram buckets.

    bounds_counts: [(upper_bound, cumulative_count)] sorted by bound,
    +Inf last. Returns the first bound whose cumulative count covers
    q * total (Prometheus-style upper-bound estimate; the +Inf bucket
    answers with the largest finite bound)."""
    if not bounds_counts:
        return None
    total = bounds_counts[-1][1]
    if total <= 0:
        return None
    target = q * total
    prev_finite = None
    for bound, cumulative in bounds_counts:
        if math.isfinite(bound):
            prev_finite = bound
        if cumulative >= target:
            return bound if math.isfinite(bound) else prev_finite
    return prev_finite


class TelemetryAggregator:
    """Background scrape/derive/export loop in the master process."""

    def __init__(
        self,
        obs_dir,
        registry=None,
        job="",
        interval=None,
        alert_engine=None,
        scrape_timeout=1.0,
    ):
        self._obs_dir = obs_dir
        self._endpoints_dir = os.path.join(obs_dir, "endpoints")
        self._registry = registry or default_registry()
        self._job = job
        if interval is None:
            interval = knobs.get_float(INTERVAL_ENV)
        self.interval = max(0.2, interval)
        self._scrape_timeout = scrape_timeout
        self.store = SeriesStore()
        self.engine = alert_engine or alerts_mod.AlertEngine(
            registry=self._registry
        )
        self._straggler_skew = alerts_mod.straggler_skew_threshold()
        self._lock = threading.Lock()
        self._summary = {"job": job, "ts": None}
        self._ewma = {}  # worker role -> EWMA step seconds
        self._gauged_workers = set()  # roles with exported per-worker gauges
        # (role, pid, port) -> consecutive scrape failures; at
        # _stale_after the endpoint is dropped until its advertisement
        # is rewritten (relaunch) or withdrawn (clean shutdown).
        self._scrape_failures = {}
        self._stale_after = max(
            1, knobs.get_int(STALE_SCRAPES_ENV)
        )
        self._throughput_history = collections.deque(maxlen=60)
        self._stop = threading.Event()
        self._thread = None
        # Store/derive mutations happen from the poll thread AND from
        # gRPC handler threads (ingest_push); one lock covers both.
        self._ingest_lock = threading.RLock()
        # (role, pid) -> {"seq", "families", "ts"}: per-origin merged
        # push state. A delta only applies when it extends the held seq.
        self._push_states = {}
        self._push_last_by_role = {}  # role -> last accepted push ts
        # role -> ts of the last ingested payload (push or pull); the
        # telemetry-freshness signal.
        self._last_report = {}
        # Endpoint directory cache: rescan only when the dir mtime moved
        # (advert add/withdraw/rewrite touches the parent dir) — O(1)
        # steady-state instead of a listdir+parse of N files per pass.
        self._ep_cache = []
        self._ep_sig = None

        reg = self._registry
        self._g_rps = reg.gauge(
            "edl_job_records_per_second",
            "Job-level training throughput (aggregated by the master)",
        )
        self._g_step = reg.gauge(
            "edl_job_step_seconds",
            "Per-worker step latency stats derived from scraped phase "
            "histograms",
            labelnames=("worker", "stat"),
        )
        self._g_straggler = reg.gauge(
            "edl_job_straggler",
            "1 while the worker is flagged as a straggler",
            labelnames=("worker",),
        )
        self._g_straggler_score = reg.gauge(
            "edl_job_straggler_score",
            "Worker step latency / fleet median",
            labelnames=("worker",),
        )
        self._g_ps_bps = reg.gauge(
            "edl_job_ps_bytes_per_second",
            "Per-PS-shard gradient/parameter byte rates",
            labelnames=("shard", "direction"),
        )
        self._g_ps_ratio = reg.gauge(
            "edl_job_ps_load_ratio",
            "PS shard byte rate / fleet median",
            labelnames=("shard",),
        )
        self._g_eta = reg.gauge(
            "edl_job_task_eta_seconds",
            "Estimated seconds until the task queue drains",
        )
        self._g_drain = reg.gauge(
            "edl_job_task_drain_per_second",
            "Task completions per second (windowed)",
        )
        self._g_mfu = reg.gauge(
            "edl_job_mfu",
            "Per-worker model FLOPs utilization estimate (re-exported)",
            labelnames=("worker",),
        )
        self._g_workers = reg.gauge(
            "edl_job_workers_reporting",
            "Worker endpoints scraped successfully on the last pass",
        )
        self._c_scrapes = reg.counter(
            "edl_job_scrapes_total",
            "Aggregator endpoint scrapes, by role",
            labelnames=("role",),
        )
        self._c_scrape_errors = reg.counter(
            "edl_job_scrape_errors_total",
            "Aggregator scrapes that failed (endpoint mid-restart, ...)",
            labelnames=("role",),
        )
        self._g_stale = reg.gauge(
            "edl_job_endpoints_stale",
            "Advertised endpoints dropped after consecutive scrape "
            "failures (dead pods whose advertisement file survived)",
        )
        self._g_compiles = reg.gauge(
            "edl_job_compiles",
            "Tracked step-function compiles summed across all scraped "
            "roles, by attributed cause",
            labelnames=("cause",),
        )
        self._g_compile_seconds = reg.gauge(
            "edl_job_compile_seconds",
            "Seconds spent compiling tracked step functions, summed "
            "across all scraped roles",
        )
        # Data-plane rollups (observability/datapath.py): fleet-level
        # views of the per-worker edl_datapath_* series.
        self._g_dp_stage = reg.gauge(
            "edl_job_datapath_stage_share",
            "Fleet-summed input-pipeline stage rate (seconds of stage "
            "time per wall second, over all workers)",
            labelnames=("stage",),
        )
        self._g_dp_records = reg.gauge(
            "edl_job_datapath_records_per_second",
            "Fleet decode throughput: records/s delivered by the input "
            "pipeline across all workers",
        )
        self._g_starve_share = reg.gauge(
            "edl_job_input_starve_share",
            "Fraction of the worker's wall time its step spent blocked "
            "on an empty feed queue",
            labelnames=("worker",),
        )
        self._g_input_starved = reg.gauge(
            "edl_job_input_starved",
            "1 while the input_starvation alert is active for the worker",
            labelnames=("worker",),
        )
        # Control-plane self-instrumentation (edl_master_*): the master
        # is itself a first-class telemetry subject at fleet scale.
        self._h_fanout = reg.histogram(
            "edl_master_scrape_fanout_seconds",
            "Wall time of the pull-scrape fan-out portion of one "
            "aggregation pass",
        )
        self._h_tick = reg.histogram(
            "edl_master_aggregation_tick_seconds",
            "Wall time of one full aggregation pass (scrape + ingest + "
            "derive)",
        )
        self._c_ep_rescans = reg.counter(
            "edl_master_endpoint_rescans_total",
            "Endpoint-directory rescans (bounded by membership events, "
            "not by aggregation passes)",
        )
        self._c_ep_diffs = reg.counter(
            "edl_master_endpoint_diffs_total",
            "Endpoint membership diffs observed on rescan",
            labelnames=("op",),
        )
        self._c_push_reports = reg.counter(
            "edl_master_push_reports_total",
            "ReportTelemetry batches handled",
        )
        self._c_push_snapshots = reg.counter(
            "edl_master_push_snapshots_total",
            "Pushed telemetry snapshots accepted, by encoding",
            labelnames=("kind",),
        )
        self._c_push_bytes = reg.counter(
            "edl_master_push_payload_bytes_total",
            "Pushed telemetry payload volume",
        )
        self._c_push_resyncs = reg.counter(
            "edl_master_push_resyncs_total",
            "Pushed deltas rejected for a sequence gap (need_full "
            "answered)",
        )
        self._g_push_roles = reg.gauge(
            "edl_master_push_roles",
            "Roles whose telemetry arrived by push within the freshness "
            "horizon",
        )
        self._g_freshness = reg.gauge(
            "edl_master_telemetry_freshness_seconds",
            "Age of the stalest reporting role's telemetry at the end "
            "of the last pass",
        )
        self._h_staleness = reg.histogram(
            "edl_master_telemetry_staleness_seconds",
            "Per-role telemetry age observed each pass",
            buckets=(0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 15.0, 30.0, 60.0),
        )

    # ---------- lifecycle ----------

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name="edl-telemetry-aggregator", daemon=True
        )
        self._thread.start()
        return self

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:
                logger.warning("Aggregation pass failed", exc_info=True)
            self._stop.wait(self.interval)

    # ---------- scraping ----------

    def discover_endpoints(self):
        """Live endpoint advertisements (the StartProfile fan-out reads
        this too; stale-skipped endpoints are excluded)."""
        return [
            info
            for info in read_endpoints(self._endpoints_dir)
            if not self._is_stale(info)
        ]

    def _endpoint_key(self, info):
        # A relaunch rewrites the advertisement with a new pid/port —
        # that is a NEW endpoint and must reset the failure count.
        return (info.get("role", ""), info.get("pid"), info.get("port"))

    def _is_stale(self, info):
        return (
            self._scrape_failures.get(self._endpoint_key(info), 0)
            >= self._stale_after
        )

    def _scrape(self, info):
        host = info.get("host") or "127.0.0.1"
        url = f"http://{host}:{info['port']}/metrics"
        return (
            urllib.request.urlopen(url, timeout=self._scrape_timeout)
            .read()
            .decode()
        )

    def _refresh_endpoints(self):
        """Cached advertisement list, rescanned only when the endpoints
        directory's mtime says an advert landed, was rewritten, or was
        withdrawn (os.replace/unlink bump the parent dir's mtime) —
        O(1) per pass steady-state, one rescan per membership event.
        The counters below make that claim test-assertable."""
        try:
            st = os.stat(self._endpoints_dir)
            sig = st.st_mtime_ns
        except OSError:
            self._ep_cache = []
            self._ep_sig = None
            return self._ep_cache
        # While the dir mtime sits inside the last second, keep
        # rescanning: coarse-mtime filesystems and a write landing in
        # the same tick would otherwise be invisible.
        if sig == self._ep_sig and (time.time() - st.st_mtime) > 1.0:
            return self._ep_cache
        before = {self._endpoint_key(i) for i in self._ep_cache}
        self._ep_cache = read_endpoints(self._endpoints_dir)
        self._ep_sig = sig
        self._c_ep_rescans.inc()
        after = {self._endpoint_key(i) for i in self._ep_cache}
        for _ in after - before:
            self._c_ep_diffs.labels(op="add").inc()
        for _ in before - after:
            self._c_ep_diffs.labels(op="withdraw").inc()
        return self._ep_cache

    def _push_horizon(self):
        """How recently a role must have pushed for the pull loop to
        leave it alone (and for it to count as push-reporting)."""
        return 3.0 * self.interval

    def _push_fresh(self, role, now):
        ts = self._push_last_by_role.get(role)
        return ts is not None and (now - ts) <= self._push_horizon()

    def poll_once(self, now=None):
        """One scrape + derive + export pass (the thread's body; callable
        directly from tests and `edl dash --once` style flows). Without
        an explicit `now`, each endpoint's samples are stamped when they
        were actually read — endpoints scrape sequentially with a
        per-endpoint timeout, and a wedged peer must not skew the rate
        denominators of everyone scraped after it. Roles with a fresh
        push are skipped here: push owns their freshness, pull stays the
        fallback when pushes stop."""
        t_tick = time.perf_counter()
        live = now is None
        scraped = set()
        stale = 0
        live_keys = set()
        texts = []  # (role, text, ts) — ingested under the lock below
        wall = time.time() if live else now
        t_fanout = time.perf_counter()
        for info in self._refresh_endpoints():
            role = info.get("role", "")
            if role == "master" and info.get("pid") == os.getpid():
                continue  # own registry is read in-process below
            key = self._endpoint_key(info)
            live_keys.add(key)
            if self._push_fresh(role, wall):
                continue
            if self._is_stale(info):
                # Dead pod whose advertisement survived (SIGKILL skips
                # the clean-shutdown removal): stop hammering the port.
                stale += 1
                continue
            try:
                text = self._scrape(info)
            except (OSError, ValueError):
                self._c_scrape_errors.labels(role=role or "?").inc()
                self._scrape_failures[key] = (
                    self._scrape_failures.get(key, 0) + 1
                )
                if self._is_stale(info):
                    stale += 1
                    logger.warning(
                        "Endpoint %s (pid %s, port %s) failed %d "
                        "consecutive scrapes; dropping it until its "
                        "advertisement is rewritten",
                        role, info.get("pid"), info.get("port"),
                        self._stale_after,
                    )
                continue
            self._scrape_failures.pop(key, None)
            texts.append((role, text, time.time() if live else now))
        self._h_fanout.observe(time.perf_counter() - t_fanout)
        # Forget failure counts of withdrawn/rewritten advertisements so
        # the map stays bounded by the live endpoint set.
        for key in list(self._scrape_failures):
            if key not in live_keys:
                del self._scrape_failures[key]
        self._g_stale.set(stale)
        now = time.time() if live else now
        with self._ingest_lock:
            for role, text, ts in texts:
                if self._ingest(role, text, ts):
                    scraped.add(role)
                    self._c_scrapes.labels(role=role or "?").inc()
            # Push-reporting roles are as good as scraped for derive.
            for role, ts in self._push_last_by_role.items():
                if (now - ts) <= self._push_horizon():
                    scraped.add(role)
            # The master's own registry never travels over HTTP:
            # reading it in-process keeps master-side signals alive
            # even when its exporter could not bind a port.
            if self._ingest("master", self._registry.expose(), now):
                scraped.add("master")
                self._c_scrapes.labels(role="master").inc()
            self._derive(now, scraped)
        self._h_tick.observe(time.perf_counter() - t_tick)

    def _ingest(self, role, text, now):
        """Parse + store one payload; False (and a scrape-error count)
        when the text does not parse — a corrupt endpoint must not be
        reported as healthy."""
        try:
            families = promtext.parse(text)
        except promtext.ParseError:
            self._c_scrape_errors.labels(role=role or "?").inc()
            return False
        self._ingest_families(role, families, now)
        return True

    def _ingest_families(self, role, families, now):
        """Store every sample of already-parsed families (the push path
        lands here directly — merged state needs no text round-trip)."""
        for family in families.values():
            # The aggregator's own edl_job_* output must not feed back
            # into its input when it ingests the master registry.
            if family.name.startswith("edl_job_"):
                continue
            for sample in family.samples:
                self.store.add(
                    role, sample.name, sample.labels, sample.value, now
                )
        if role != "master":
            self._last_report[role] = now

    # ---------- push ingestion ----------

    def ingest_push(self, snapshots, origin="", now=None):
        """Apply one ReportTelemetry batch; -> (accepted, need_full).

        Each snapshot is a pb.TelemetrySnapshot (or any object/dict with
        the same fields). Fulls replace the per-(role, pid) state;
        deltas must extend the held sequence (seq == last+1) or the
        role lands on the need_full list and the reporter resends a
        full snapshot next push. The merged state — not the delta — is
        ingested each time, so the series store ends up exactly where a
        pull scrape of the same registry would have put it."""
        wall = time.time() if now is None else now
        accepted = 0
        need_full = set()
        self._c_push_reports.inc()
        with self._ingest_lock:
            for snap in snapshots:
                role = _snap_field(snap, "role", "")
                pid = _snap_field(snap, "pid", 0)
                seq = _snap_field(snap, "seq", 0)
                full = _snap_field(snap, "full", False)
                payload = _snap_field(snap, "payload", "")
                key = (role, pid)
                self._c_push_bytes.inc(len(payload))
                try:
                    delta = (
                        promtext.parse(payload)
                        if payload
                        else collections.OrderedDict()
                    )
                except promtext.ParseError:
                    self._c_scrape_errors.labels(role=role or "?").inc()
                    need_full.add(role)
                    continue
                state = self._push_states.get(key)
                if full:
                    state = {"seq": seq, "families": delta, "ts": wall}
                    self._push_states[key] = state
                    self._c_push_snapshots.labels(kind="full").inc()
                elif state is None or seq != state["seq"] + 1:
                    # Lost/reordered push (or a master restart): the
                    # held state no longer matches what the reporter
                    # diffed against.
                    self._c_push_resyncs.inc()
                    need_full.add(role)
                    continue
                else:
                    push_mod.apply_delta(state["families"], delta)
                    state["seq"] = seq
                    state["ts"] = wall
                    self._c_push_snapshots.labels(kind="delta").inc()
                self._ingest_families(role, state["families"], wall)
                self._push_last_by_role[role] = wall
                accepted += 1
        return accepted, sorted(need_full)

    # ---------- derivation ----------

    def _worker_roles(self):
        return [r for r in self.store.roles() if r.startswith("worker")]

    def _ps_roles(self):
        return [r for r in self.store.roles() if r.startswith("ps")]

    def _step_labels(self):
        return (("phase", "batch_process"),)

    def _worker_step_stats(self, role, now=None):
        """Windowed step-time stats for one worker from its scraped
        edl_phase_seconds{phase="batch_process"} histogram."""
        labels = self._step_labels()
        dsum = self.store.delta(
            role, "edl_phase_seconds_sum", labels, now=now
        )
        dcount = self.store.delta(
            role, "edl_phase_seconds_count", labels, now=now
        )
        if not dcount or dsum is None or dcount < MIN_STEP_SAMPLES:
            return None
        mean = dsum / dcount
        bounds = []
        for s_labels in self.store.labelsets(
            role, "edl_phase_seconds_bucket"
        ):
            label_map = dict(s_labels)
            if label_map.get("phase") != "batch_process":
                continue
            le = label_map.get("le", "")
            bound = math.inf if le == "+Inf" else float(le)
            delta = self.store.delta(
                role, "edl_phase_seconds_bucket", s_labels, now=now
            )
            if delta is not None:
                bounds.append((bound, delta))
        bounds.sort(key=lambda bc: bc[0])
        p50 = histogram_quantile(bounds, 0.50)
        p99 = histogram_quantile(bounds, 0.99)
        ewma = self._ewma.get(role)
        ewma = (
            mean
            if ewma is None
            else _EWMA_ALPHA * mean + (1 - _EWMA_ALPHA) * ewma
        )
        self._ewma[role] = ewma
        return {
            "mean": mean,
            "p50": p50,
            "p99": p99,
            "ewma": ewma,
            "steps_in_window": dcount,
        }

    def _derive(self, now, scraped):
        # --- throughput ---
        rps = self.store.rate("master", "edl_records_done", now=now)
        if rps is not None:
            self._g_rps.set(rps)
            self._throughput_history.append(
                (round(now, 3), round(rps, 3))
            )
        records_done = self.store.latest("master", "edl_records_done")

        # --- per-worker step time + stragglers ---
        workers = {}
        step_means = {}
        for role in self._worker_roles():
            stats = self._worker_step_stats(role, now)
            if stats is None:
                continue
            workers[role] = stats
            step_means[role] = stats["ewma"]
            for stat in ("mean", "p50", "p99", "ewma"):
                value = stats[stat]
                if value is not None:
                    self._g_step.labels(worker=role, stat=stat).set(value)
            mfu = self.store.latest(role, "edl_worker_mfu")
            if mfu is not None:
                workers[role]["mfu"] = mfu
                self._g_mfu.labels(worker=role).set(mfu)
        straggler_scores = skew_scores(step_means)
        for role, score in straggler_scores.items():
            self._g_straggler_score.labels(worker=role).set(score)
            workers[role]["straggler_score"] = round(score, 3)

        # --- PS shard load ---
        ps = {}
        ps_rates = {}
        for role in self._ps_roles():
            # Per-shard byte counters carry labels (shard, rpc): fold
            # every labeled series of the family into one per-role rate.
            push = self._family_rate(
                role, "edl_ps_push_bytes_total", now=now
            )
            pull = self._family_rate(
                role, "edl_ps_pull_bytes_total", now=now
            )
            if push is None and pull is None:
                continue
            ps[role] = {
                "push_bytes_per_second": push,
                "pull_bytes_per_second": pull,
            }
            ps_rates[role] = (push or 0.0) + (pull or 0.0)
            if push is not None:
                self._g_ps_bps.labels(shard=role, direction="push").set(
                    push
                )
            if pull is not None:
                self._g_ps_bps.labels(shard=role, direction="pull").set(
                    pull
                )
        ps_skew = skew_scores(ps_rates)
        for role, ratio in ps_skew.items():
            self._g_ps_ratio.labels(shard=role).set(ratio)
            ps[role]["load_ratio"] = round(ratio, 3)

        # --- task queue drain / ETA ---
        todo = self.store.latest("master", "edl_tasks_todo")
        doing = self.store.latest("master", "edl_tasks_doing")
        # Success reports only: failed tasks are requeued, so counting
        # them as drain would make the ETA optimistic exactly during the
        # incidents this dashboard diagnoses.
        drain = self.store.rate(
            "master",
            "edl_tasks_reported_total",
            (("result", "success"),),
            now=now,
        )
        eta = None
        if drain and todo is not None:
            eta = (todo + (doing or 0)) / drain
            self._g_eta.set(eta)
        if drain is not None:
            self._g_drain.set(drain)
        abandoned = self._family_total(
            "master", "edl_tasks_abandoned_total"
        )
        recovered = self._family_total(
            "master", "edl_tasks_recovered_total"
        )

        # --- compile accounting (the profiling plane, aggregated) ---
        # Sum the per-role edl_compile_* counters over EVERY scraped
        # role so one master scrape answers "how much recompiling did
        # this elastic job do, and why".
        compile_counts = {}  # cause -> count
        compile_seconds = 0.0
        for role in self.store.roles():
            for labels in self.store.labelsets(role, "edl_compile_total"):
                value = self.store.latest(
                    role, "edl_compile_total", labels
                )
                if value:
                    cause = dict(labels).get("cause", "?")
                    compile_counts[cause] = (
                        compile_counts.get(cause, 0) + value
                    )
            for labels in self.store.labelsets(
                role, "edl_compile_seconds_total"
            ):
                value = self.store.latest(
                    role, "edl_compile_seconds_total", labels
                )
                if value:
                    compile_seconds += value
        for cause, count in compile_counts.items():
            self._g_compiles.labels(cause=cause).set(count)
        self._g_compile_seconds.set(compile_seconds)

        # --- data-plane rollups (observability/datapath.py) ---
        # Per-stage rates are seconds-of-stage-time per wall second, so
        # the per-worker `starve` rate reads directly as "fraction of
        # this worker's wall time the step sat on an empty feed".
        dp_stage_rates = {}
        starve_shares = {}
        dp_records_rate = None
        dp_queue_depth = {}
        dp_backpressure = None
        for role in self.store.roles():
            if not role.startswith("worker"):
                continue
            for labels in self.store.labelsets(
                role, "edl_datapath_seconds_total"
            ):
                rate = self.store.rate(
                    role, "edl_datapath_seconds_total", labels, now=now
                )
                if rate is None:
                    continue
                stage = dict(labels).get("stage", "?")
                dp_stage_rates[stage] = (
                    dp_stage_rates.get(stage, 0.0) + rate
                )
                if stage == "starve":
                    starve_shares[role] = (
                        starve_shares.get(role, 0.0) + rate
                    )
            rec_rate = self._family_rate(
                role, "edl_datapath_records_total", now=now
            )
            if rec_rate is not None:
                dp_records_rate = (dp_records_rate or 0.0) + rec_rate
            for labels in self.store.labelsets(
                role, "edl_datapath_queue_depth"
            ):
                depth = self.store.latest(
                    role, "edl_datapath_queue_depth", labels
                )
                if depth is not None:
                    qname = dict(labels).get("queue", "?")
                    dp_queue_depth[f"{role}/{qname}"] = depth
            bp = self._family_total(
                role, "edl_datapath_backpressure_total"
            )
            if bp is not None:
                dp_backpressure = (dp_backpressure or 0.0) + bp
        for stage, rate in dp_stage_rates.items():
            self._g_dp_stage.labels(stage=stage).set(rate)
        if dp_records_rate is not None:
            self._g_dp_records.set(dp_records_rate)
        dominant_stage = (
            max(dp_stage_rates, key=dp_stage_rates.get)
            if dp_stage_rates
            else None
        )

        # --- alerts ---
        signals = {
            "records_per_second": rps,
            "records_done": records_done,
            "straggler_scores": straggler_scores,
            "ps_skew_scores": ps_skew,
            "tasks_abandoned": abandoned,
            "tasks_todo": todo,
            "tasks_doing": doing,
            "input_starve_shares": starve_shares,
        }
        self.engine.evaluate(signals, now)
        flagged = set(self.engine.active_subjects("straggler"))
        for role in step_means:
            is_straggler = role in flagged
            self._g_straggler.labels(worker=role).set(
                1 if is_straggler else 0
            )
            workers[role]["straggler"] = is_straggler
        starved = set(self.engine.active_subjects("input_starvation"))
        for role, share in starve_shares.items():
            self._g_starve_share.labels(worker=role).set(share)
            self._g_input_starved.labels(worker=role).set(
                1 if role in starved else 0
            )
        # A worker that stopped reporting (scaled away, dead) must not
        # pin ANY of its per-worker gauges on /metrics forever — and its
        # EWMA must not seed a relaunched instance's scoring.
        for role in self._gauged_workers - set(step_means):
            self._g_straggler.labels(worker=role).set(0)
            self._g_straggler_score.labels(worker=role).set(0)
            for stat in ("mean", "p50", "p99", "ewma"):
                self._g_step.labels(worker=role, stat=stat).set(0)
            self._g_mfu.labels(worker=role).set(0)
            if role not in starve_shares:
                self._g_starve_share.labels(worker=role).set(0)
                self._g_input_starved.labels(worker=role).set(0)
            self._ewma.pop(role, None)
        self._gauged_workers |= set(step_means)
        self._g_workers.set(len(workers))

        # --- telemetry freshness + fleet rollups ---
        # Per-role age of the last ingested payload (push or pull).
        # Roles silent for 30 intervals are dead/scaled away and leave
        # the freshness sample set (their series age out via rate()'s
        # staleness window already).
        freshness = {}
        for role, ts in list(self._last_report.items()):
            age = now - ts
            if age > 30.0 * self.interval:
                del self._last_report[role]
                continue
            freshness[role] = age
            self._h_staleness.observe(max(0.0, age))
        # _derive always runs under _ingest_lock (re-entrant), but take
        # it explicitly here: these maps are also written by the gRPC
        # handler path and the pruning must visibly share that guard.
        with self._ingest_lock:
            for key, state in list(self._push_states.items()):
                if now - state["ts"] > 30.0 * self.interval:
                    del self._push_states[key]
            for role, ts in list(self._push_last_by_role.items()):
                if now - ts > 30.0 * self.interval:
                    del self._push_last_by_role[role]
        push_roles = sum(
            1
            for ts in self._push_last_by_role.values()
            if (now - ts) <= self._push_horizon()
        )
        self._g_push_roles.set(push_roles)
        ages = sorted(freshness.values())
        fresh_max = ages[-1] if ages else None
        if fresh_max is not None:
            self._g_freshness.set(fresh_max)
        step_vals = sorted(step_means.values())
        fleet = {
            "workers_reporting": len(workers),
            "ps_reporting": len(ps),
            "roles_reporting": len(freshness),
            "push_roles": push_roles,
            "pull_roles": max(0, len(freshness) - push_roles),
            "step_ewma_p50": percentile(step_vals, 0.50),
            "step_ewma_p90": percentile(step_vals, 0.90),
            "step_ewma_p99": percentile(step_vals, 0.99),
            "freshness_max_s": (
                None if fresh_max is None else round(fresh_max, 3)
            ),
            "freshness_p99_s": (
                None
                if not ages
                else round(percentile(ages, 0.99), 3)
            ),
        }

        membership_epoch = self.store.latest(
            "master", "edl_membership_epoch"
        )
        summary = {
            "job": self._job,
            "ts": round(now, 3),
            "interval_s": self.interval,
            "records_per_second": rps,
            "records_done": records_done,
            "throughput_history": list(self._throughput_history),
            "workers": workers,
            "stragglers": sorted(flagged),
            "straggler_skew_threshold": self._straggler_skew,
            "ps": ps,
            "tasks": {
                "todo": todo,
                "doing": doing,
                "drain_per_second": drain,
                "eta_seconds": eta,
                "abandoned": abandoned,
                "recovered": recovered,
            },
            "alerts": self.engine.active(),
            "alerts_fired": self.engine.fired_total,
            "membership_epoch": membership_epoch,
            "roles_scraped": sorted(scraped),
            "fleet": fleet,
            "compiles": {
                "total": sum(compile_counts.values()),
                "by_cause": compile_counts,
                "edl_compile_seconds_total": round(compile_seconds, 4),
            },
            # Empty until workers report edl_datapath_* series (older
            # workers, ELASTICDL_DATAPATH=0): consumers skip the panel.
            "datapath": (
                {
                    "stages": {
                        s: round(v, 4)
                        for s, v in sorted(dp_stage_rates.items())
                    },
                    "dominant_stage": dominant_stage,
                    "records_per_second": dp_records_rate,
                    "starve_shares": {
                        r: round(v, 4)
                        for r, v in sorted(starve_shares.items())
                    },
                    "starved": sorted(starved),
                    "queue_depth": dp_queue_depth,
                    "backpressure_total": dp_backpressure,
                }
                if dp_stage_rates or dp_records_rate is not None
                else {}
            ),
        }
        with self._lock:
            self._summary = summary

    def _family_rate(self, role, name, window_s=RATE_WINDOW_S,
                     now=None):
        """Sum of rate() across every labeled series of one family."""
        total = None
        for labels in self.store.labelsets(role, name):
            rate = self.store.rate(
                role, name, labels, window_s, now=now
            )
            if rate is not None:
                total = (total or 0.0) + rate
        return total

    def _family_total(self, role, name):
        total = None
        for labels in self.store.labelsets(role, name):
            value = self.store.latest(role, name, labels)
            if value is not None:
                total = (total or 0.0) + value
        return total

    # ---------- consumption ----------

    def summary(self):
        """JSON-able snapshot for /api/summary and `edl dash`."""
        with self._lock:
            return dict(self._summary)

    def stragglers(self):
        """Worker roles currently flagged (JobStatusResponse field)."""
        return self.engine.active_subjects("straggler")

    def alerts_fired(self):
        return self.engine.fired_total
