"""Size-capped rotation for the append-only observability logs.

`traces.jsonl`/`events.jsonl` grow unbounded in long jobs — a week-long
online-learning run would eat the disk with spans nobody will read.
`SizeCappedFile` gives both writers the same policy: when the live file
crosses the cap it is atomically renamed to `<path>.1` (replacing the
previous generation — total footprint is bounded by ~2x the cap) and a
fresh file is opened, so at least one cap's worth of the most recent
history always survives. The writer is told about each rotation so it
can stamp a marker record (the `rotated` event / trace metadata line)
into the new generation — readers then know the stream has a cut, not a
gap.

The cap comes from ELASTICDL_OBS_MAX_LOG_MB (0 disables rotation).
Thread-safety is the CALLER's job (both writers already serialize under
their own lock — this object is their locked internals).
"""

import os

from elasticdl_tpu.common import knobs

MAX_LOG_MB_ENV = "ELASTICDL_OBS_MAX_LOG_MB"


def max_log_bytes():
    mb = knobs.get_float(MAX_LOG_MB_ENV)
    return int(mb * (1 << 20)) if mb > 0 else 0


class SizeCappedFile:
    """Line-append file with single-generation size rotation."""

    def __init__(self, path, max_bytes=None, on_rotate=None):
        self.path = path
        self.max_bytes = (
            max_log_bytes() if max_bytes is None else max_bytes
        )
        self.rotations = 0
        self._on_rotate = on_rotate
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._file = open(path, "a", buffering=1)
        try:
            self._size = os.path.getsize(path)
        except OSError:
            self._size = 0

    @property
    def closed(self):
        return self._file.closed

    def maybe_rotate(self, upcoming_len):
        """Rotate now if a record of `upcoming_len` bytes would push the
        live file past the cap. Split out of write_line for writers that
        must stamp per-record state (the event log's seq) AFTER the
        rotation marker: check first, then build + append the record."""
        if self._file.closed:
            return
        if (
            self.max_bytes
            and self._size
            and self._size + upcoming_len + 1 > self.max_bytes
        ):
            self._rotate()

    def append_line(self, line):
        """Raw append without a rotation check (callers paired it with
        maybe_rotate, or are the rotation callback itself)."""
        if self._file.closed:
            return
        self._file.write(line + "\n")
        # Byte length, not character length: the cap and the initial
        # getsize() are bytes, and non-ASCII payloads would otherwise
        # under-count and overshoot the cap on disk.
        self._size += len(line.encode("utf-8", "replace")) + 1

    def write_line(self, line):
        """Append one newline-terminated record, rotating first when the
        record would push the live file past the cap."""
        self.maybe_rotate(len(line.encode("utf-8", "replace")))
        self.append_line(line)

    def _rotate(self):
        self._file.close()
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass  # rotation must never kill the writer
        self._file = open(self.path, "a", buffering=1)
        self._size = 0
        self.rotations += 1
        if self._on_rotate is not None:
            try:
                self._on_rotate(self.rotations)
            except Exception:
                pass

    def close(self):
        if not self._file.closed:
            self._file.close()
