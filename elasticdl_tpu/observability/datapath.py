"""Stage-level data-plane instrumentation for the input pipeline.

The bench attribution table (BENCH_r08) puts `input_wait` at 0.24-0.40
of every PS-mode step, but it is a single opaque bucket: nothing says
whether the time went to waiting on the master for a task lease, to the
record reader, to decode, or to the h2d copy. This module decomposes the
feed path into named stages and lands every stage three ways at once:

- a `Timing` phase (``input_<stage>``) on whatever Timing object the
  call site binds, so `bench/attribution.py` can split `input_wait`
  into sub-fractions from the same phase summaries it already reads;
- a tracing span (``datapath.<stage>``) so Perfetto shows the feed
  path interleaved with train_step/push/pull spans;
- Prometheus series: `edl_datapath_seconds_total{stage}` and
  `edl_datapath_records_total` for fleet rollups (per-worker
  starvation share, decode throughput), plus a per-stage duration
  histogram `edl_datapath_stage_seconds{stage}`.

Stage model (docs/OBSERVABILITY.md "Data plane"):

    task     waiting on the master for a task lease (get_task RPC wait)
    read     pulling raw records out of the reader/storage
    decode   parsing records into arrays (InputSpec.feed)
    collate  assembling rows/batches from already-read records
    h2d      host-to-device transfer of the built batch
    starve   trainer blocked on an EMPTY prefetch queue (the step could
             not start because no batch was ready)

`read` vs `starve`: with the prefetch pipeline on (the default), the
producer thread owns `read` and the consumer's wait on the hand-off
queue is `starve` — the signal a perf PR acts on. Without prefetch the
consumer's pull IS the read, and starve stays zero.

Hand-off queues additionally report occupancy through `QueueTelemetry`:
an `edl_datapath_queue_depth{queue}` gauge plus edge-triggered
high-watermark events (`datapath_backpressure`) and an
`edl_datapath_backpressure_total{queue}` counter when a bounded queue
crosses ELASTICDL_DATAPATH_QUEUE_WATERMARK of its capacity.

Overhead is bounded by design: one wall-clock timestamp pair and a
counter bump per stage; ELASTICDL_DATAPATH=0 turns every stage() into a
no-op yield.
"""

import contextlib
import threading
import time

from elasticdl_tpu.common import knobs
from elasticdl_tpu.observability import emit_event, tracing
from elasticdl_tpu.observability.metrics import default_registry

DATAPATH_ENV = "ELASTICDL_DATAPATH"
QUEUE_CAPACITY_ENV = "ELASTICDL_DATAPATH_QUEUE_CAPACITY"
QUEUE_WATERMARK_ENV = "ELASTICDL_DATAPATH_QUEUE_WATERMARK"

# Canonical stage names; the Timing phase is "input_<stage>" so the
# bench attribution layer can bucket them under input_wait.
STAGES = ("task", "read", "decode", "collate", "h2d", "starve")

# Stage-duration buckets: feed stages live in the 50us..1s range, well
# below the latency-shaped registry default (1ms..100s).
_STAGE_BUCKETS = (
    5e-5, 2e-4, 1e-3, 4e-3, 0.016, 0.064, 0.25, 1.0, 4.0,
)

_registry = default_registry()
_SECONDS = _registry.counter(
    "edl_datapath_seconds_total",
    "Wall seconds spent per input-pipeline stage",
    labelnames=("stage",),
)
_RECORDS = _registry.counter(
    "edl_datapath_records_total",
    "Records delivered by the input pipeline",
)
_STAGE_HIST = _registry.histogram(
    "edl_datapath_stage_seconds",
    "Per-call duration of each input-pipeline stage",
    labelnames=("stage",),
    buckets=_STAGE_BUCKETS,
)
_QUEUE_DEPTH = _registry.gauge(
    "edl_datapath_queue_depth",
    "Current occupancy of an input-pipeline hand-off queue",
    labelnames=("queue",),
)
_BACKPRESSURE = _registry.counter(
    "edl_datapath_backpressure_total",
    "High-watermark crossings of an input-pipeline hand-off queue",
    labelnames=("queue",),
)


class _Stage:
    """Mutable holder yielded by stage(); the body sets .records to the
    number of records the stage delivered (counted ONCE per record, at
    the delivery boundary — producers and transforms leave it 0)."""

    __slots__ = ("records",)

    def __init__(self, records=0):
        self.records = records


class Datapath:
    """Per-process data-plane instrumentation hub.

    One instance per process (module singleton via get()); Timing
    mirroring is per-call-site — pass `timing=` so the phase lands on
    the Timing object whose summary the caller reports (the worker loop
    Timing for read/decode, the trainer's own Timing for h2d)."""

    def __init__(self, enabled=None):
        if enabled is None:
            enabled = knobs.get_int(DATAPATH_ENV) != 0
        self._enabled = bool(enabled)
        self._timing = None
        self._lock = threading.Lock()
        # Per-flush accumulation for the `datapath` event trail:
        # {stage: seconds} plus a record count, swapped out whole by
        # flush_event() at task boundaries.
        self._acc = {}
        self._acc_records = 0

    @property
    def enabled(self):
        return self._enabled

    def bind_timing(self, timing):
        """Default Timing object for stages that do not pass their own."""
        self._timing = timing

    @contextlib.contextmanager
    def stage(self, name, records=0, timing=None):
        """Time one stage execution. Yields a holder whose .records the
        body may set once the delivered record count is known."""
        holder = _Stage(records)
        if not self._enabled:
            yield holder
            return
        start = time.time()
        try:
            yield holder
        finally:
            dur = time.time() - start
            tracing.record_span(
                "datapath." + name, start, dur, cat="datapath"
            )
            self.add(name, dur, records=holder.records, timing=timing)

    def add(self, name, seconds, records=0, timing=None):
        """Account an already-measured stage interval (for producer
        threads that time with their own clock pair)."""
        if not self._enabled or seconds < 0:
            return
        _SECONDS.labels(stage=name).inc(seconds)
        _STAGE_HIST.labels(stage=name).observe(seconds)
        if records:
            _RECORDS.inc(records)
        t = timing if timing is not None else self._timing
        if t is not None:
            t.add("input_" + name, seconds)
        with self._lock:
            self._acc[name] = self._acc.get(name, 0.0) + seconds
            self._acc_records += records

    def flush_event(self, **extra):
        """Emit one `datapath` event carrying the per-stage seconds
        accumulated since the last flush (called at task boundaries so
        the event trail stays one line per task, not per batch)."""
        if not self._enabled:
            return
        with self._lock:
            acc, self._acc = self._acc, {}
            records, self._acc_records = self._acc_records, 0
        if not acc and not records:
            return
        fields = {f"{k}_s": round(v, 6) for k, v in sorted(acc.items())}
        emit_event("datapath", records=records, **fields, **extra)


class QueueTelemetry:
    """Occupancy/backpressure telemetry for one bounded hand-off queue.

    depth() sets the `edl_datapath_queue_depth{queue}` gauge and fires
    an edge-triggered `datapath_backpressure` event (plus counter) when
    occupancy first crosses the high watermark; it re-arms once depth
    falls back below the mark, so a saturated queue costs one event per
    excursion, not one per put."""

    def __init__(self, name, capacity=None, datapath=None):
        self.name = name
        if capacity is None:
            capacity = knobs.get_int(QUEUE_CAPACITY_ENV)
        self.capacity = int(capacity) if capacity else 0
        ratio = knobs.get_float(QUEUE_WATERMARK_ENV)
        self._mark = (
            self.capacity * ratio if self.capacity and ratio > 0 else 0
        )
        self._armed = True
        self._dp = datapath
        self._gauge = _QUEUE_DEPTH.labels(queue=name)
        self._counter = _BACKPRESSURE.labels(queue=name)

    def depth(self, d):
        dp = self._dp if self._dp is not None else get()
        if not dp.enabled:
            return
        self._gauge.set(d)
        if not self._mark:
            return
        if d >= self._mark:
            if self._armed:
                self._armed = False
                self._counter.inc()
                emit_event(
                    "datapath_backpressure",
                    queue=self.name,
                    depth=int(d),
                    capacity=self.capacity,
                )
        else:
            self._armed = True


_singleton = None
_singleton_lock = threading.Lock()


def get():
    """The process-global Datapath instance (created on first use, so
    the ELASTICDL_DATAPATH gate is read after the process environment is
    fully set up)."""
    global _singleton
    if _singleton is None:
        with _singleton_lock:
            if _singleton is None:
                _singleton = Datapath()
    return _singleton
