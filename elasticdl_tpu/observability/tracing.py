"""Cross-process tracing: Chrome-trace JSONL spans + gRPC context propagation.

Each process appends complete ("ph": "X") events to its own
`trace_<role>.jsonl`; `tools/trace_report.py` merges the per-process files
into one Chrome-trace JSON loadable in Perfetto / chrome://tracing. The
trace "pid" is a stable hash of the process's role string — NOT the OS pid —
so the master / each PS / each worker get distinct, deterministic process
rows even when a test hosts several roles inside one interpreter.

Trace context is a contextvar carrying (trace_id, span_id, job, task_id,
lease_epoch). The client interceptor injects it into gRPC metadata
(`edl-trace-*` keys); the server interceptor extracts it and runs the
handler under it, so one task's dispatch -> pull -> train -> push -> report
chain shares a trace id across every process it touches. Propagation is
always on (a few string pairs per RPC); recording costs nothing until
observability.setup() installs a recorder.
"""

import contextlib
import contextvars
import json
import os
import threading
import time
import uuid
import zlib

import grpc

# Metadata keys must be lowercase in gRPC.
_MD_TRACE = "edl-trace-id"
_MD_PARENT = "edl-parent-span"
_MD_TASK = "edl-task-id"
_MD_EPOCH = "edl-lease-epoch"
_MD_JOB = "edl-job"

_context = contextvars.ContextVar("edl_trace_context", default=None)

_recorder = None

# Secondary span consumers (the flight recorder). Sinks receive every
# span the plane observes — (name, start_s, dur_s, cat, args) — even
# when no file recorder is installed, and must be cheap + non-raising.
_sinks = []


def add_sink(sink):
    if sink not in _sinks:
        _sinks.append(sink)


def remove_sink(sink):
    if sink in _sinks:
        _sinks.remove(sink)


def _feed_sinks(name, start_s, dur_s, cat, args):
    for sink in list(_sinks):
        try:
            sink(name, start_s, dur_s, cat, args)
        except Exception:
            pass


class TraceContext:
    __slots__ = ("trace_id", "span_id", "job", "task_id", "lease_epoch")

    def __init__(
        self, trace_id=None, span_id="", job="", task_id=-1, lease_epoch=-1
    ):
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.span_id = span_id
        self.job = job
        self.task_id = task_id
        self.lease_epoch = lease_epoch

    def args(self):
        out = {"trace_id": self.trace_id}
        if self.job:
            out["job"] = self.job
        if self.task_id >= 0:
            out["task_id"] = self.task_id
        if self.lease_epoch >= 0:
            out["lease_epoch"] = self.lease_epoch
        return out


def set_context(task_id=None, lease_epoch=None, job=None, trace_id=None):
    """Create/refresh this thread's trace context; returns it. Starting a
    new task (task_id given, different from the current one) mints a new
    trace id so each task forms its own trace tree."""
    ctx = _context.get()
    if ctx is None or (
        trace_id is not None and trace_id != ctx.trace_id
    ) or (
        task_id is not None and task_id != ctx.task_id
    ):
        ctx = TraceContext(
            trace_id=trace_id,
            job=job if job is not None else (ctx.job if ctx else ""),
            task_id=task_id if task_id is not None else -1,
            lease_epoch=(
                lease_epoch
                if lease_epoch is not None
                else (ctx.lease_epoch if ctx else -1)
            ),
        )
        _context.set(ctx)
        return ctx
    if job is not None:
        ctx.job = job
    if lease_epoch is not None:
        ctx.lease_epoch = lease_epoch
    return ctx


def clear_context():
    _context.set(None)


def role_pid(role):
    """Deterministic per-role trace pid (distinct process rows in the
    merged trace even when several roles share one OS process)."""
    return zlib.crc32(role.encode()) & 0x7FFFFFF


class SpanRecorder:
    """Appends Chrome-trace events to a JSONL file; thread-safe.
    Size-capped (observability/rotation.py): a rotated generation keeps
    the previous cap's worth of spans as <path>.1 and re-stamps the
    process-name metadata plus a `rotated` marker so the fresh file is
    independently loadable in Perfetto."""

    def __init__(self, path, process_name, max_bytes=None):
        from elasticdl_tpu.observability.rotation import SizeCappedFile

        self.path = path
        self.process_name = process_name
        self.pid = role_pid(process_name)
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._file = SizeCappedFile(
            path, max_bytes=max_bytes, on_rotate=self._on_rotate
        )
        # Perfetto reads process names from this metadata event.
        self._write(self._process_meta())

    def _process_meta(self):
        return {
            "ph": "M",
            "name": "process_name",
            "pid": self.pid,
            "tid": 0,
            "args": {"name": self.process_name},
        }

    def _on_rotate(self, generation):
        # Runs under self._lock mid-write (rotation.py callback): these
        # are the new generation's first lines.
        for event in (
            self._process_meta(),
            {
                "ph": "i",
                "s": "p",
                "name": "rotated",
                "cat": "edl",
                "ts": round(time.time() * 1e6, 1),
                "pid": self.pid,
                "tid": 0,
                "args": {"generation": generation},
            },
        ):
            self._file.append_line(
                json.dumps(event, separators=(",", ":"))
            )

    def _write(self, event):
        line = json.dumps(event, separators=(",", ":"))
        with self._lock:
            if self._file.closed:
                return
            self._file.write_line(line)

    def record(self, name, start_s, dur_s, cat="edl", args=None):
        """One complete span; times in seconds (perf-epoch: time.time)."""
        ctx = _context.get()
        merged = ctx.args() if ctx is not None else {}
        if args:
            merged.update(args)
        self._write(
            {
                "ph": "X",
                "name": name,
                "cat": cat,
                "ts": round(start_s * 1e6, 1),
                "dur": round(dur_s * 1e6, 1),
                "pid": self.pid,
                "tid": threading.get_ident() & 0xFFFF,
                "args": merged,
            }
        )

    def instant(self, name, cat="edl", args=None):
        ctx = _context.get()
        merged = ctx.args() if ctx is not None else {}
        if args:
            merged.update(args)
        self._write(
            {
                "ph": "i",
                "s": "p",
                "name": name,
                "cat": cat,
                "ts": round(time.time() * 1e6, 1),
                "pid": self.pid,
                "tid": threading.get_ident() & 0xFFFF,
                "args": merged,
            }
        )

    def close(self):
        with self._lock:
            if not self._file.closed:
                self._file.close()


def set_recorder(recorder):
    global _recorder
    _recorder = recorder


def get_recorder():
    return _recorder


@contextlib.contextmanager
def span(name, cat="edl", **args):
    """Record a span around the with-body (no-op without a recorder or
    sink; the body's exceptions still propagate and the span still
    closes)."""
    rec = _recorder
    if rec is None and not _sinks:
        yield
        return
    start = time.time()
    try:
        yield
    finally:
        dur = time.time() - start
        if rec is not None:
            rec.record(name, start, dur, cat=cat, args=args)
        _feed_sinks(name, start, dur, cat, args)


def record_span(name, start_s, dur_s, cat="edl", args=None):
    """Record an already-measured span (recorder + sinks). For callers
    that time the interval themselves — e.g. the compile tracker, which
    only knows a call was a compile once it returns."""
    rec = _recorder
    if rec is not None:
        rec.record(name, start_s, dur_s, cat=cat, args=args)
    _feed_sinks(name, start_s, dur_s, cat, args)


def instant(name, cat="edl", **args):
    rec = _recorder
    if rec is not None:
        rec.instant(name, cat=cat, args=args)


# ---------- gRPC propagation ----------


def _inject(metadata):
    ctx = _context.get()
    if ctx is None:
        return metadata
    extra = [(_MD_TRACE, ctx.trace_id)]
    if ctx.span_id:
        extra.append((_MD_PARENT, ctx.span_id))
    if ctx.job:
        extra.append((_MD_JOB, ctx.job))
    if ctx.task_id >= 0:
        extra.append((_MD_TASK, str(ctx.task_id)))
    if ctx.lease_epoch >= 0:
        extra.append((_MD_EPOCH, str(ctx.lease_epoch)))
    return list(metadata or ()) + extra


def context_from_metadata(metadata):
    """TraceContext extracted from invocation metadata, or None."""
    md = {k: v for k, v in (metadata or ())}
    trace_id = md.get(_MD_TRACE)
    if trace_id is None:
        return None
    return TraceContext(
        trace_id=trace_id,
        span_id=md.get(_MD_PARENT, ""),
        job=md.get(_MD_JOB, ""),
        task_id=int(md.get(_MD_TASK, -1)),
        lease_epoch=int(md.get(_MD_EPOCH, -1)),
    )


class _ClientCallDetails(grpc.ClientCallDetails):
    def __init__(self, base, metadata):
        self.method = base.method
        self.timeout = base.timeout
        self.metadata = metadata
        self.credentials = base.credentials
        self.wait_for_ready = getattr(base, "wait_for_ready", None)
        self.compression = getattr(base, "compression", None)


class TracingClientInterceptor(grpc.UnaryUnaryClientInterceptor):
    """Injects the caller's trace context and records a client span."""

    def intercept_unary_unary(self, continuation, details, request):
        new_details = _ClientCallDetails(
            details, _inject(details.metadata)
        )
        rec = _recorder
        if rec is None and not _sinks:
            return continuation(new_details, request)
        start = time.time()
        call = continuation(new_details, request)

        # Record at response time so the span covers the full RPC. Futures
        # returned by stub.method.future() are recorded when they resolve.
        def done(c, s=start):
            dur = time.time() - s
            args = {"code": str(c.code())}
            if rec is not None:
                rec.record(
                    f"rpc_client{details.method}", s, dur, cat="rpc",
                    args=args,
                )
            _feed_sinks(
                f"rpc_client{details.method}", s, dur, "rpc", args
            )

        call.add_done_callback(done)
        return call


class TracingServerInterceptor(grpc.ServerInterceptor):
    """Runs each handler under the caller's propagated trace context and
    records a server span."""

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None or handler.unary_unary is None:
            return handler
        inner = handler.unary_unary
        method = handler_call_details.method

        def traced(request, context):
            ctx = context_from_metadata(
                context.invocation_metadata()
            )
            token = None
            if ctx is not None:
                token = _context.set(ctx)
            try:
                rec = _recorder
                if rec is None and not _sinks:
                    return inner(request, context)
                start = time.time()
                try:
                    return inner(request, context)
                finally:
                    dur = time.time() - start
                    if rec is not None:
                        rec.record(
                            f"rpc_server{method}", start, dur, cat="rpc"
                        )
                    _feed_sinks(
                        f"rpc_server{method}", start, dur, "rpc", None
                    )
            finally:
                if token is not None:
                    _context.reset(token)

        return grpc.unary_unary_rpc_method_handler(
            traced,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )
