"""Unified observability plane shared by master, PS, and worker processes.

Three pillars, zero third-party dependencies:

- metrics:   a process-local registry of Counter/Gauge/Histogram exposed in
             Prometheus text-exposition format over a tiny stdlib HTTP
             endpoint (exporter.py).
- tracing:   Chrome-trace/Perfetto-compatible spans written as JSONL per
             process, with trace context (job, task id, lease epoch)
             propagated across gRPC hops via the interceptors installed by
             common/rpc.py.
- events:    a structured elasticity event log (pod launch/exit/relaunch,
             lease grant/report/abort, task create/timeout/reassign)
             appended as events.jsonl alongside the job's metrics.jsonl.

`setup()` configures all three for one process and is called by
master/main.py, ps/main.py, and worker/main.py. Components never import the
exporter directly — they use `default_registry()`, `emit_event()`, and
`tracing.span()`, all of which are cheap no-ops until configured (events,
traces) or always-on but unexported (metrics). Configuration travels to
spawned worker/PS processes via the ELASTICDL_OBS_DIR / ELASTICDL_JOB_NAME
environment variables (set by the master before it launches instances).
"""

import json
import os

from elasticdl_tpu.common import knobs
from elasticdl_tpu.observability import events as _events
from elasticdl_tpu.observability import tracing as _tracing
from elasticdl_tpu.observability.metrics import default_registry  # noqa: F401

OBS_DIR_ENV = "ELASTICDL_OBS_DIR"
JOB_NAME_ENV = "ELASTICDL_JOB_NAME"
METRICS_PORT_ENV = "ELASTICDL_METRICS_PORT"

emit_event = _events.emit

_handle = None


class ObservabilityHandle:
    """One process's configured observability plane."""

    def __init__(self, role, job, obs_dir, exporter, recorder, event_log,
                 flight=None, memory=None):
        self.role = role
        self.job = job
        self.obs_dir = obs_dir
        self.exporter = exporter
        self.recorder = recorder
        self.event_log = event_log
        self.flight = flight
        self.memory = memory

    @property
    def metrics_port(self):
        return self.exporter.port if self.exporter is not None else 0

    def close(self):
        global _handle
        if self.flight is not None:
            from elasticdl_tpu.observability import flightrec

            if flightrec.get() is self.flight:
                flightrec.uninstall()
        if self.memory is not None:
            self.memory.close()
        if self.exporter is not None:
            self.exporter.close()
            # Clean shutdown withdraws the endpoint advertisement so the
            # master's aggregator stops scraping a port nobody serves
            # (crashed pods are handled by its stale-endpoint counter).
            # Only when the file is still OURS: a relaunched successor
            # with the same role may have rewritten it, and deleting the
            # live advert would silently unplug that process. (A
            # microsecond read-then-remove window remains — POSIX has no
            # compare-and-unlink — accepted: the successor would have to
            # advertise inside it, and the failure needs BOTH processes
            # shutting down/starting in that instant.)
            if self.obs_dir:
                path = os.path.join(
                    self.obs_dir, "endpoints", f"{self.role}.json"
                )
                try:
                    with open(path) as f:
                        advertised = json.load(f)
                    if advertised.get("pid") == os.getpid():
                        os.remove(path)
                except (OSError, ValueError):
                    pass
        if self.recorder is not None:
            self.recorder.close()
            if _tracing.get_recorder() is self.recorder:
                _tracing.set_recorder(None)
        if self.event_log is not None:
            if _events.get_event_log() is self.event_log:
                _events.set_event_log(None)
            self.event_log.close()
        if _handle is self:
            _handle = None


def current_handle():
    return _handle


def setup(role, job="", obs_dir=None, metrics_port=None, registry=None):
    """Configure this process's observability plane and return its handle.

    obs_dir=None reads ELASTICDL_OBS_DIR; still-None disables traces and
    events but keeps the in-process metrics registry live (and exported,
    when metrics_port says so). metrics_port=None reads
    ELASTICDL_METRICS_PORT; 0 binds an ephemeral port; a negative value
    disables the endpoint. The bound endpoint is advertised under
    <obs_dir>/endpoints/<role>.json so monitors and tests can find every
    process of a job without guessing ports.

    Idempotent: a second setup() in the same process returns the first
    call's live handle unchanged (double wiring would double-register
    exporters and samplers). Port-collision-safe: a fixed metrics_port
    that is already bound falls back to an ephemeral port and the
    advertisement carries whatever port actually bound.
    """
    global _handle
    if _handle is not None:
        return _handle
    from elasticdl_tpu.common import log_utils
    from elasticdl_tpu.observability.exporter import MetricsExporter
    from elasticdl_tpu.observability.metrics import default_registry

    if obs_dir is None:
        obs_dir = knobs.get_str(OBS_DIR_ENV)
    if not job:
        job = knobs.get_str(JOB_NAME_ENV)
    if metrics_port is None:
        metrics_port = knobs.get_int(METRICS_PORT_ENV)
    log_utils.set_identity(job=job, role=role)
    # Instrumented roles that already pulled in jax get the persistent
    # compilation cache wired here (recompile-free elasticity). Gated on
    # jax being imported so a jax-free control plane (the master) never
    # pays a multi-hundred-MB jax import for a cache it cannot use —
    # compiling roles that set up BEFORE importing jax (worker, PS) wire
    # it at their trainer/server construction instead.
    import sys as _sys

    if "jax" in _sys.modules:
        from elasticdl_tpu.common.compile_cache import (
            ensure_compile_cache,
        )

        ensure_compile_cache()

    recorder = None
    event_log = None
    flight = None
    if obs_dir:
        os.makedirs(obs_dir, exist_ok=True)
        recorder = _tracing.SpanRecorder(
            os.path.join(obs_dir, f"trace_{role}.jsonl"),
            process_name=f"{job}/{role}" if job else role,
        )
        _tracing.set_recorder(recorder)
        event_log = _events.EventLog(
            os.path.join(obs_dir, "events.jsonl"), job=job, role=role
        )
        _events.set_event_log(event_log)
        # Crash-dump flight recorder: a bounded ring of the spans the
        # plane just started emitting, dumped to
        # <obs_dir>/flightrec-<role>.json on crash/SIGTERM so a dead
        # role leaves attributable evidence (ELASTICDL_FLIGHTREC=0
        # disables).
        from elasticdl_tpu.observability import flightrec

        flight = flightrec.install(role, dump_dir=obs_dir)

    exporter = None
    if metrics_port >= 0:
        try:
            exporter = MetricsExporter(
                registry or default_registry(), port=metrics_port
            )
        except OSError:
            # A busy fixed port must not kill (or silence) a training
            # process: fall back to an ephemeral port and re-advertise —
            # scrapers find endpoints through the advertisement file,
            # not the configured number.
            log_utils.get_logger("observability").warning(
                "Could not bind metrics endpoint on port %d; falling "
                "back to an ephemeral port", metrics_port,
            )
            try:
                exporter = MetricsExporter(
                    registry or default_registry(), port=0
                )
            except OSError:
                log_utils.get_logger("observability").warning(
                    "Could not bind any metrics endpoint; metrics stay "
                    "in-process only"
                )
    if exporter is not None:
        # On-demand device profiling for this role: every exporter
        # answers /debug/profile, capturing into <obs_dir>/profiles/
        # (or ./profiles without an obs dir).
        from elasticdl_tpu.observability import profiling

        exporter.profile_provider = profiling.profile_provider(
            obs_dir, role
        )
    if obs_dir and exporter is not None:
        _advertise_endpoint(obs_dir, role, job, exporter.port)

    # Memory accountant: live/peak device + host RSS gauges and
    # high-watermark events, sampled on a daemon thread
    # (ELASTICDL_MEM_SAMPLE_SECONDS=0 disables the thread; the
    # process-global accountant still answers direct sample() calls).
    from elasticdl_tpu.observability import memory as _memory

    mem = _memory.accountant().start()

    _handle = ObservabilityHandle(
        role, job, obs_dir, exporter, recorder, event_log, flight,
        memory=mem,
    )
    return _handle


def _scrape_host():
    bind = knobs.get_str("ELASTICDL_METRICS_HOST")
    if bind and bind not in ("0.0.0.0", "::"):
        return bind
    return os.environ.get("MY_POD_IP", "127.0.0.1")


def _advertise_endpoint(obs_dir, role, job, port):
    endpoints = os.path.join(obs_dir, "endpoints")
    os.makedirs(endpoints, exist_ok=True)
    path = os.path.join(endpoints, f"{role}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(
            {
                "role": role,
                "job": job,
                "pid": os.getpid(),
                "port": port,
                # Scrape host for off-host monitors (the aggregator):
                # an explicit non-wildcard bind address wins (the
                # exporter only listens there), then the pod IP, then
                # localhost.
                "host": _scrape_host(),
            },
            f,
        )
    os.replace(tmp, path)  # atomic: readers never see a partial file
