"""Unified observability plane shared by master, PS, and worker processes.

Three pillars, zero third-party dependencies:

- metrics:   a process-local registry of Counter/Gauge/Histogram exposed in
             Prometheus text-exposition format over a tiny stdlib HTTP
             endpoint (exporter.py).
- tracing:   Chrome-trace/Perfetto-compatible spans written as JSONL per
             process, with trace context (job, task id, lease epoch)
             propagated across gRPC hops via the interceptors installed by
             common/rpc.py.
- events:    a structured elasticity event log (pod launch/exit/relaunch,
             lease grant/report/abort, task create/timeout/reassign)
             appended as events.jsonl alongside the job's metrics.jsonl.

`setup()` configures all three for one process and is called by
master/main.py, ps/main.py, and worker/main.py. Components never import the
exporter directly — they use `default_registry()`, `emit_event()`, and
`tracing.span()`, all of which are cheap no-ops until configured (events,
traces) or always-on but unexported (metrics). Configuration travels to
spawned worker/PS processes via the ELASTICDL_OBS_DIR / ELASTICDL_JOB_NAME
environment variables (set by the master before it launches instances).
"""

import json
import os

from elasticdl_tpu.common import knobs
from elasticdl_tpu.observability import events as _events
from elasticdl_tpu.observability import tracing as _tracing
from elasticdl_tpu.observability.metrics import default_registry  # noqa: F401

OBS_DIR_ENV = "ELASTICDL_OBS_DIR"
JOB_NAME_ENV = "ELASTICDL_JOB_NAME"
METRICS_PORT_ENV = "ELASTICDL_METRICS_PORT"

emit_event = _events.emit

_handle = None


class ObservabilityHandle:
    """One process's configured observability plane."""

    def __init__(self, role, job, obs_dir, exporter, recorder, event_log,
                 flight=None):
        self.role = role
        self.job = job
        self.obs_dir = obs_dir
        self.exporter = exporter
        self.recorder = recorder
        self.event_log = event_log
        self.flight = flight

    @property
    def metrics_port(self):
        return self.exporter.port if self.exporter is not None else 0

    def close(self):
        global _handle
        if self.flight is not None:
            from elasticdl_tpu.observability import flightrec

            if flightrec.get() is self.flight:
                flightrec.uninstall()
        if self.exporter is not None:
            self.exporter.close()
        if self.recorder is not None:
            self.recorder.close()
            if _tracing.get_recorder() is self.recorder:
                _tracing.set_recorder(None)
        if self.event_log is not None:
            if _events.get_event_log() is self.event_log:
                _events.set_event_log(None)
            self.event_log.close()
        if _handle is self:
            _handle = None


def current_handle():
    return _handle


def setup(role, job="", obs_dir=None, metrics_port=None, registry=None):
    """Configure this process's observability plane and return its handle.

    obs_dir=None reads ELASTICDL_OBS_DIR; still-None disables traces and
    events but keeps the in-process metrics registry live (and exported,
    when metrics_port says so). metrics_port=None reads
    ELASTICDL_METRICS_PORT; 0 binds an ephemeral port; a negative value
    disables the endpoint. The bound endpoint is advertised under
    <obs_dir>/endpoints/<role>.json so monitors and tests can find every
    process of a job without guessing ports.
    """
    global _handle
    if _handle is not None:
        return _handle
    from elasticdl_tpu.common import log_utils
    from elasticdl_tpu.observability.exporter import MetricsExporter
    from elasticdl_tpu.observability.metrics import default_registry

    if obs_dir is None:
        obs_dir = knobs.get_str(OBS_DIR_ENV)
    if not job:
        job = knobs.get_str(JOB_NAME_ENV)
    if metrics_port is None:
        metrics_port = knobs.get_int(METRICS_PORT_ENV)
    log_utils.set_identity(job=job, role=role)

    recorder = None
    event_log = None
    flight = None
    if obs_dir:
        os.makedirs(obs_dir, exist_ok=True)
        recorder = _tracing.SpanRecorder(
            os.path.join(obs_dir, f"trace_{role}.jsonl"),
            process_name=f"{job}/{role}" if job else role,
        )
        _tracing.set_recorder(recorder)
        event_log = _events.EventLog(
            os.path.join(obs_dir, "events.jsonl"), job=job, role=role
        )
        _events.set_event_log(event_log)
        # Crash-dump flight recorder: a bounded ring of the spans the
        # plane just started emitting, dumped to
        # <obs_dir>/flightrec-<role>.json on crash/SIGTERM so a dead
        # role leaves attributable evidence (ELASTICDL_FLIGHTREC=0
        # disables).
        from elasticdl_tpu.observability import flightrec

        flight = flightrec.install(role, dump_dir=obs_dir)

    exporter = None
    if metrics_port >= 0:
        try:
            exporter = MetricsExporter(
                registry or default_registry(), port=metrics_port
            )
        except OSError:
            # A busy fixed port must not kill a training process; the
            # metrics stay collectable in-process (and via the next
            # relaunch, which may land on a free port).
            log_utils.get_logger("observability").warning(
                "Could not bind metrics endpoint on port %d", metrics_port
            )
    if obs_dir and exporter is not None:
        _advertise_endpoint(obs_dir, role, job, exporter.port)

    _handle = ObservabilityHandle(
        role, job, obs_dir, exporter, recorder, event_log, flight
    )
    return _handle


def _scrape_host():
    bind = knobs.get_str("ELASTICDL_METRICS_HOST")
    if bind and bind not in ("0.0.0.0", "::"):
        return bind
    return os.environ.get("MY_POD_IP", "127.0.0.1")


def _advertise_endpoint(obs_dir, role, job, port):
    endpoints = os.path.join(obs_dir, "endpoints")
    os.makedirs(endpoints, exist_ok=True)
    path = os.path.join(endpoints, f"{role}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(
            {
                "role": role,
                "job": job,
                "pid": os.getpid(),
                "port": port,
                # Scrape host for off-host monitors (the aggregator):
                # an explicit non-wildcard bind address wins (the
                # exporter only listens there), then the pod IP, then
                # localhost.
                "host": _scrape_host(),
            },
            f,
        )
    os.replace(tmp, path)  # atomic: readers never see a partial file
