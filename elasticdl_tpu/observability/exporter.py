"""Stdlib-only Prometheus scrape endpoint.

One daemonized ThreadingHTTPServer per process serving:

    /metrics        the registry in text-exposition format
    /healthz        "ok" — a liveness probe target for k8s pod specs
    /api/summary    job-level JSON summary (master only — present when a
                    TelemetryAggregator installed a summary provider)
    /debug/profile  on-demand jax.profiler capture of this process
                    (?seconds=N; present when observability.setup()
                    installed a profile provider)

GET and HEAD are both answered (k8s http probes default to HEAD; a 501
there flaps the pod). No third-party dependency: the exposition format is
plain text and the stdlib HTTP server is enough for a scraper that polls
every few seconds.

Binds ELASTICDL_METRICS_HOST (default 0.0.0.0 — a scrape endpoint is only
useful off-host; CI/sandbox runs set 127.0.0.1) on the requested port;
port 0 picks an ephemeral port, published via `.port` and the endpoints/
advertisement written by observability.setup().
"""

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from elasticdl_tpu.common import knobs

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

METRICS_HOST_ENV = "ELASTICDL_METRICS_HOST"


class _Handler(BaseHTTPRequestHandler):
    registry = None
    exporter = None

    def _respond(self, code, body, content_type, send_body=True):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if send_body:
            self.wfile.write(body)

    def _serve(self, send_body):
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            body = self.registry.expose().encode()
            self._respond(200, body, CONTENT_TYPE, send_body)
        elif path == "/healthz":
            self._respond(200, b"ok\n", "text/plain", send_body)
        elif path == "/api/summary":
            provider = self.exporter.summary_provider
            if provider is None:
                self.send_error(404)
                return
            t0 = time.perf_counter()
            try:
                body = json.dumps(provider()).encode()
            except Exception:
                # A half-updated summary must not kill the probe endpoint.
                self.send_error(500)
                return
            # Only the master carries a summary provider, so this series
            # appears exactly where it is meaningful: the cost of
            # rendering /api/summary grows with fleet size and `edl
            # dash` polls it every interval.
            self.registry.histogram(
                "edl_master_summary_render_seconds",
                "Time to render the /api/summary JSON body",
            ).observe(time.perf_counter() - t0)
            self._respond(200, body, "application/json", send_body)
        elif path == "/debug/profile":
            # On-demand jax.profiler capture of THIS process
            # (?seconds=N, default 2): blocks the requesting connection
            # for the capture duration — the server is threaded, so
            # concurrent /metrics scrapes keep answering. 409 when a
            # capture is already running, 404 when the role has no
            # provider (observability.setup() not run).
            provider = getattr(self.exporter, "profile_provider", None)
            if provider is None:
                self.send_error(404)
                return
            if not send_body:
                # A HEAD must not burn an N-second capture (and a
                # profile directory) just to answer headers.
                self.send_error(405)
                return
            query = urllib.parse.parse_qs(
                urllib.parse.urlparse(self.path).query
            )
            try:
                seconds = float(query.get("seconds", ["2.0"])[0])
            except ValueError:
                seconds = 2.0
            try:
                body = json.dumps(provider(seconds)).encode()
            except RuntimeError:
                self.send_error(409)  # capture already in flight
                return
            except Exception:
                self.send_error(500)
                return
            self._respond(200, body, "application/json", send_body)
        else:
            self.send_error(404)

    def do_GET(self):
        self._serve(send_body=True)

    def do_HEAD(self):
        # Same status/headers as GET, no body (k8s probes use HEAD).
        self._serve(send_body=False)

    def log_message(self, format, *args):
        # Scrapes every few seconds must not spam the training log.
        pass


class MetricsExporter:
    def __init__(self, registry, port=0, host=None):
        if host is None:
            host = knobs.get_str(METRICS_HOST_ENV) or "0.0.0.0"
        # Installed post-construction by the master's TelemetryAggregator;
        # callable returning a JSON-able dict for /api/summary.
        self.summary_provider = None
        # Installed by observability.setup(): callable(seconds) -> dict
        # backing /debug/profile (on-demand jax.profiler capture).
        self.profile_provider = None
        handler = type(
            "_BoundHandler",
            (_Handler,),
            {"registry": registry, "exporter": self},
        )
        self._server = ThreadingHTTPServer((host, port), handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="edl-metrics-exporter",
            daemon=True,
        )
        self._thread.start()

    def close(self):
        self._server.shutdown()
        self._server.server_close()
