"""Stdlib-only Prometheus scrape endpoint.

One daemonized ThreadingHTTPServer per process serving:

    /metrics   the registry in text-exposition format
    /healthz   "ok" — a liveness probe target for k8s pod specs

No third-party dependency: the exposition format is plain text and the
stdlib HTTP server is enough for a scraper that polls every few seconds.
Binds 0.0.0.0 (a scrape endpoint is only useful off-host) on the requested
port; port 0 picks an ephemeral port, published via `.port` and the
endpoints/ advertisement written by observability.setup().
"""

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    registry = None

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            body = self.registry.expose().encode()
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/healthz":
            body = b"ok\n"
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_error(404)

    def log_message(self, format, *args):
        # Scrapes every few seconds must not spam the training log.
        pass


class MetricsExporter:
    def __init__(self, registry, port=0, host="0.0.0.0"):
        handler = type("_BoundHandler", (_Handler,), {"registry": registry})
        self._server = ThreadingHTTPServer((host, port), handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="edl-metrics-exporter",
            daemon=True,
        )
        self._thread.start()

    def close(self):
        self._server.shutdown()
        self._server.server_close()
