"""Push-based telemetry: delta-encoded metric snapshots from workers/PS
to the master's ReportTelemetry RPC.

The pull model (aggregator scrapes every /metrics endpoint each interval)
costs the master O(n) HTTP round-trips and full-text parses per tick — at
500 pods the scrape fan-out dominates the control plane. This module
inverts the flow: each reporting process keeps the families it last sent
(`TelemetryPusher`) and ships only the samples whose values changed since
(`snapshot_delta`), on a jittered interval (`TelemetryReporter`) so the
fleet doesn't dogpile the master in lockstep. The master merges deltas
back into per-origin state with `apply_delta` and ingests the merged
families directly — no text parse on the hot path.

Loss recovery is sequence-numbered: every snapshot carries a per-process
`seq`; the master accepts a delta only when it extends the state it holds
(seq == last+1) and otherwise answers `need_full`, which makes the
reporter resend a full snapshot next push. Every Nth push is full anyway
(ELASTICDL_TELEMETRY_FULL_EVERY) to bound the resync horizon.

Deltas never need tombstones: a MetricsRegistry only ever grows samples
(counters/gauges persist once created), so "changed or new" covers the
whole state evolution.
"""

import collections
import os
import random
import threading

from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import promtext

logger = get_logger(__name__)

PUSH_INTERVAL_ENV = "ELASTICDL_TELEMETRY_PUSH_INTERVAL"
PUSH_JITTER_ENV = "ELASTICDL_TELEMETRY_PUSH_JITTER"
FULL_EVERY_ENV = "ELASTICDL_TELEMETRY_FULL_EVERY"


def snapshot_delta(prev_families, cur_families):
    """Families holding only the samples that changed (or are new) in
    `cur_families` relative to `prev_families`; families with no changed
    samples are omitted entirely. Both sides are promtext-style ordered
    {name: MetricFamily} dicts; the inputs are not mutated."""
    prev_values = {}
    for family in prev_families.values():
        for s in family.samples:
            prev_values[(s.name, s.labels)] = s.value
    delta = collections.OrderedDict()
    for name, family in cur_families.items():
        changed = [
            s for s in family.samples
            if prev_values.get((s.name, s.labels)) != s.value
        ]
        if changed:
            out = promtext.MetricFamily(family.name, family.type, family.help)
            out.samples = changed
            delta[name] = out
    return delta


def apply_delta(state_families, delta_families):
    """Merge a delta into `state_families` in place (and return it).
    Changed samples replace their (name, labels) slot; new samples and
    families append, preserving the order both sides emitted them in."""
    for name, family in delta_families.items():
        cur = state_families.get(name)
        if cur is None:
            cur = promtext.MetricFamily(family.name, family.type, family.help)
            state_families[name] = cur
        index = {
            (s.name, s.labels): i for i, s in enumerate(cur.samples)
        }
        for s in family.samples:
            i = index.get((s.name, s.labels))
            if i is None:
                cur.samples.append(s)
            else:
                cur.samples[i] = s
    return state_families


class TelemetryPusher:
    """Delta-encoding state machine for one process's registry.

    `snapshot()` returns the kwargs for one pb.TelemetrySnapshot (the
    proto module is deliberately not imported here so the fleet harness
    and tests can use pushers without gRPC). `reset()` forces the next
    snapshot to be full — call it when the master answers need_full.
    """

    def __init__(self, registry, role, full_every=None):
        self._registry = registry
        self.role = role
        self.pid = os.getpid()
        self._seq = 0
        self._last = None  # families as of the last snapshot sent
        if full_every is None:
            full_every = knobs.get_int(FULL_EVERY_ENV)
        self._full_every = max(0, int(full_every))
        self._lock = threading.Lock()

    def reset(self):
        with self._lock:
            self._last = None

    def snapshot(self):
        """-> {role, pid, seq, full, payload} for one TelemetrySnapshot.
        An unchanged registry still yields a (payload-empty) delta: the
        push doubles as the role's freshness heartbeat."""
        families = promtext.parse(self._registry.expose())
        with self._lock:
            self._seq += 1
            full = self._last is None or (
                self._full_every and self._seq % self._full_every == 0
            )
            payload_families = (
                families if full else snapshot_delta(self._last, families)
            )
            self._last = families
            seq = self._seq
        payload = promtext.to_text(payload_families) if payload_families else ""
        return {
            "role": self.role,
            "pid": self.pid,
            "seq": seq,
            "full": bool(full),
            "payload": payload,
        }


class TelemetryReporter:
    """Background push loop for one process: snapshot the registry on a
    jittered interval and report it through `report_fn` (typically
    MasterClient.report_telemetry). Failures are counted and retried on
    the next tick — telemetry must never take a trainer down."""

    def __init__(self, report_fn, registry, role,
                 interval=None, jitter=None, full_every=None, seed=None):
        self._report = report_fn
        self._pusher = TelemetryPusher(registry, role, full_every=full_every)
        self.role = role
        if interval is None:
            interval = knobs.get_float(PUSH_INTERVAL_ENV)
        if jitter is None:
            jitter = knobs.get_float(PUSH_JITTER_ENV)
        self.interval = float(interval)
        self._jitter = max(0.0, float(jitter))
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread = None
        self.pushes = 0
        self.errors = 0

    @property
    def enabled(self):
        return self.interval > 0

    def start(self):
        if not self.enabled or self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name=f"telemetry-push-{self.role}", daemon=True
        )
        self._thread.start()
        return self

    def push_once(self):
        """One synchronous push; True when the master accepted it."""
        snap = self._pusher.snapshot()
        try:
            resp = self._report([snap], origin=self.role)
        except Exception as e:  # gRPC errors must not leak to the trainer
            self.errors += 1
            logger.debug("telemetry push failed: %s", e)
            return False
        self.pushes += 1
        if resp is not None and self.role in tuple(
            getattr(resp, "need_full", ())
        ):
            self._pusher.reset()
        return True

    def _run(self):
        while not self._stop.is_set():
            wait = self.interval
            if self._jitter:
                wait *= 1.0 + self._jitter * (2.0 * self._rng.random() - 1.0)
            if self._stop.wait(max(0.01, wait)):
                break
            self.push_once()

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
