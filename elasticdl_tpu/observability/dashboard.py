"""Terminal dashboard rendering for `edl dash` / `edl top --watch`.

Pure text: takes the /api/summary JSON dict the master's aggregator
publishes (plus an optional JobStatusResponse) and renders one frame —
per-worker step-time bars with straggler flags, a throughput sparkline,
PS shard load bars, task queue/ETA, active alerts, membership epoch. No
curses dependency: frames are plain strings; the watch loop clears the
screen with ANSI codes, and `--once` prints exactly one frame (the
testable mode).
"""

import json
import shutil
import urllib.request

SPARK_CHARS = "▁▂▃▄▅▆▇█"
BAR_CHAR = "█"


def fetch_summary(host, port, timeout=2.0):
    """GET the master exporter's /api/summary."""
    url = f"http://{host}:{port}/api/summary"
    with urllib.request.urlopen(url, timeout=timeout) as res:
        return json.loads(res.read().decode())


def sparkline(values, width=32):
    """Last `width` values as unicode block characters."""
    values = [v for v in values if v is not None][-width:]
    if not values:
        return ""
    lo, hi = min(values), max(values)
    span = hi - lo
    out = []
    for v in values:
        idx = (
            0
            if span <= 0
            else int((v - lo) / span * (len(SPARK_CHARS) - 1))
        )
        out.append(SPARK_CHARS[idx])
    return "".join(out)


def bar(value, scale, width=24):
    """A left-aligned bar of value/scale, clamped to width cells."""
    if not scale or scale <= 0 or value is None:
        return ""
    cells = int(round(min(1.0, value / scale) * width))
    return BAR_CHAR * max(cells, 1 if value > 0 else 0)


def _fmt_seconds(s):
    if s is None:
        return "-"
    if s >= 3600:
        return f"{s / 3600:.1f}h"
    if s >= 60:
        return f"{s / 60:.1f}m"
    if s >= 1:
        return f"{s:.1f}s"
    return f"{s * 1000:.0f}ms"


def _fmt_rate(v, unit=""):
    if v is None:
        return "-"
    for factor, suffix in ((1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(v) >= factor:
            return f"{v / factor:.1f}{suffix}{unit}"
    return f"{v:.1f}{unit}"


def render(summary, status=None, width=None, top=0):
    """One dashboard frame as a string (no trailing clear codes).

    `top` caps the per-worker and per-PS sections to the K worst rows
    (slowest workers, busiest shards) — at 300+ pods a full roster is
    unreadable and the fleet rollup line carries the rest. 0 shows
    everything (the historical behavior)."""
    if width is None:
        width = shutil.get_terminal_size((100, 24)).columns
    width = max(60, width)
    lines = []
    job = summary.get("job") or "?"
    rps = summary.get("records_per_second")
    records = summary.get("records_done")
    header = f"job {job}"
    if status is not None:
        header += (
            f"  epoch {status.epoch}/{status.num_epochs}"
            f"  v{status.model_version}"
            f"  workers={status.alive_workers}"
        )
        if status.membership_epoch:
            header += f"  mepoch={status.membership_epoch}"
    elif summary.get("membership_epoch"):
        header += f"  mepoch={int(summary['membership_epoch'])}"
    lines.append(header)
    lines.append("─" * min(width, len(header) + 12))

    history = [v for _, v in summary.get("throughput_history") or []]
    lines.append(
        f"throughput {_fmt_rate(rps, ' rec/s'):>12}  "
        f"{sparkline(history)}  records={int(records or 0)}"
    )

    tasks = summary.get("tasks") or {}
    lines.append(
        f"tasks todo={_int(tasks.get('todo'))} "
        f"doing={_int(tasks.get('doing'))} "
        f"drain={_fmt_rate(tasks.get('drain_per_second'), '/s')} "
        f"eta={_fmt_seconds(tasks.get('eta_seconds'))} "
        f"recovered={_int(tasks.get('recovered'))} "
        f"abandoned={_int(tasks.get('abandoned'))}"
    )

    fleet = summary.get("fleet") or {}
    if fleet.get("roles_reporting"):
        lines.append(
            f"fleet roles={_int(fleet.get('roles_reporting'))} "
            f"(push={_int(fleet.get('push_roles'))} "
            f"pull={_int(fleet.get('pull_roles'))})  "
            f"step p50/p90/p99="
            f"{_fmt_seconds(fleet.get('step_ewma_p50'))}/"
            f"{_fmt_seconds(fleet.get('step_ewma_p90'))}/"
            f"{_fmt_seconds(fleet.get('step_ewma_p99'))}  "
            f"telemetry age max={_fmt_seconds(fleet.get('freshness_max_s'))} "
            f"p99={_fmt_seconds(fleet.get('freshness_p99_s'))}"
        )

    workers = summary.get("workers") or {}
    if workers:
        lines.append("")
        shown = sorted(workers)
        if top and len(workers) > top:
            # Slowest-first: at fleet scale the interesting rows are
            # the stragglers; the fleet line above covers the healthy
            # majority.
            shown = sorted(
                workers,
                key=lambda r: workers[r].get("ewma") or 0,
                reverse=True,
            )[:top]
            lines.append(
                f"worker step time (ewma) — slowest {top} of "
                f"{len(workers)}"
            )
        else:
            lines.append("worker step time (ewma)")
        scale = max(
            (w.get("ewma") or 0) for w in workers.values()
        ) or None
        for role in shown:
            w = workers[role]
            ewma = w.get("ewma")
            flags = ""
            if w.get("straggler"):
                flags = (
                    f"  ⚠ STRAGGLER x{w.get('straggler_score', '?')}"
                )
            mfu = w.get("mfu")
            mfu_txt = f"  mfu={mfu * 100:.1f}%" if mfu else ""
            lines.append(
                f"  {role:<12} {_fmt_seconds(ewma):>8} "
                f"p50={_fmt_seconds(w.get('p50'))} "
                f"p99={_fmt_seconds(w.get('p99'))}  "
                f"{bar(ewma, scale)}{flags}{mfu_txt}"
            )

    ps = summary.get("ps") or {}
    if ps:
        lines.append("")
        totals = {
            role: (s.get("push_bytes_per_second") or 0)
            + (s.get("pull_bytes_per_second") or 0)
            for role, s in ps.items()
        }
        shown = sorted(ps)
        if top and len(ps) > top:
            shown = sorted(
                ps, key=lambda r: totals[r], reverse=True
            )[:top]
            lines.append(
                f"ps shard load (push+pull bytes/s) — busiest {top} "
                f"of {len(ps)}"
            )
        else:
            lines.append("ps shard load (push+pull bytes/s)")
        scale = max(totals.values()) or None
        for role in shown:
            s = ps[role]
            ratio = s.get("load_ratio")
            ratio_txt = f"  x{ratio}" if ratio is not None else ""
            lines.append(
                f"  {role:<12} {_fmt_rate(totals[role], 'B/s'):>10}  "
                f"{bar(totals[role], scale)}{ratio_txt}"
            )

    dp = summary.get("datapath") or {}
    if dp:
        lines.append("")
        stages = dp.get("stages") or {}
        head = "data plane"
        if dp.get("records_per_second") is not None:
            head += (
                f" {_fmt_rate(dp.get('records_per_second'), ' rec/s')}"
            )
        if dp.get("dominant_stage"):
            head += f"  slowest stage: {dp['dominant_stage']}"
        if dp.get("backpressure_total"):
            head += (
                f"  backpressure={_int(dp.get('backpressure_total'))}"
            )
        lines.append(head)
        if stages:
            lines.append(
                "  "
                + "  ".join(
                    f"{s}={v:.3f}" for s, v in sorted(stages.items())
                )
                + "  (stage-seconds per wall second, fleet)"
            )
        starve = dp.get("starve_shares") or {}
        starved = set(dp.get("starved") or [])
        worst = sorted(
            starve, key=lambda r: starve[r], reverse=True
        )[: top or len(starve)]
        for role in worst:
            share = starve[role]
            if not share and role not in starved:
                continue
            flag = "  ⚠ STARVED" if role in starved else ""
            lines.append(
                f"  {role:<12} starve={share * 100:5.1f}%  "
                f"{bar(share, 1.0)}{flag}"
            )
        queues = dp.get("queue_depth") or {}
        if queues:
            depth_txt = " ".join(
                f"{q}={_int(d)}" for q, d in sorted(queues.items())
            )
            lines.append(f"  queue depth: {depth_txt}"[:width])

    policy = summary.get("policy") or {}
    if policy.get("enabled"):
        lines.append("")
        head = (
            f"policy actions={_int(policy.get('actions_total'))} "
            f"ticks={_int(policy.get('ticks'))}"
        )
        if policy.get("dry_run"):
            head += "  DRY-RUN"
        blacklisted = policy.get("blacklisted") or []
        if blacklisted:
            head += (
                "  blacklist="
                + ",".join(str(w) for w in blacklisted)
            )
        if policy.get("backups_inflight"):
            head += f"  backups={_int(policy.get('backups_inflight'))}"
        if policy.get("backup_wins"):
            head += f"  backup_wins={_int(policy.get('backup_wins'))}"
        hint = policy.get("world_hint") or {}
        if hint.get("seq"):
            head += (
                f"  hint=world {hint.get('target_world_size')}"
                f" ({_fmt_seconds(hint.get('age_seconds'))} ago)"
            )
        lines.append(head)
        now_ts = summary.get("ts")
        for d in (policy.get("recent") or [])[-4:]:
            age_txt = ""
            if now_ts is not None and d.get("ts") is not None:
                age_txt = f" {_fmt_seconds(max(0, now_ts - d['ts']))} ago"
            lines.append(
                f"  {d.get('action')}[{d.get('subject')}] "
                f"{d.get('outcome')}{age_txt}: {d.get('reason')}"
            )

    alerts = summary.get("alerts") or []
    lines.append("")
    if alerts:
        lines.append(
            f"alerts active={len(alerts)} "
            f"fired={_int(summary.get('alerts_fired'))}"
        )
        for a in alerts:
            detail = {
                k: v
                for k, v in a.items()
                if k not in ("rule", "subject")
            }
            lines.append(f"  ⚠ {a['rule']}: {a['subject']} {detail}")
    else:
        lines.append(
            f"alerts none (fired={_int(summary.get('alerts_fired'))})"
        )
    if status is not None and (status.finished or status.job_failed):
        lines.append("")
        lines.append("JOB FAILED" if status.job_failed else "JOB FINISHED")
    return "\n".join(line[:width] for line in lines)


def _int(v):
    return int(v) if v is not None else 0


CLEAR = "\x1b[2J\x1b[H"
