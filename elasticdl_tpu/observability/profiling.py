"""Deep profiling plane, part 1: compile accounting + on-demand device
profiles.

The observability arc so far sees the job from the outside (RPC spans,
scraped metrics, push phase splits) but is blind below the JAX boundary.
This module opens that boundary in two ways:

Compile tracker
    `tracked_jit(fn, name=...)` replaces every direct `jax.jit`/`pjit`
    in the worker/parallel/ps trainer paths (the `compile-tracker` lint
    rule enforces the replacement). The wrapper keys each call on the
    (shape, dtype) signature of its arguments plus the current *mesh
    fingerprint* (`note_mesh()`, stamped by the elastic trainer on every
    world change) and attributes each lowering to a cause:

        cold           first compile of this step function ever
        mesh_change    the mesh/world fingerprint moved since the last
                       compile (elastic regroup re-lowering the step)
        shape_change   same mesh, new argument shapes (ragged batch,
                       new eval shape)
        rebuild        a rebuilt jit object re-lowering a signature this
                       process already compiled (checkpoint restore,
                       forward rebuild)
        donation_miss  XLA's own cache grew on an already-seen signature
                       (donation/weak-type/tree retrace) — the silent
                       recompile class the wrapper exists to surface

    Each compile lands in three places: `edl_compile_total{fn,cause}` /
    `edl_compile_seconds_total{fn,cause}` counters, a `compile` event in
    events.jsonl, and a `compile:<fn>` span (cat "compile") in the trace
    — so a regroup's recompile stall is visible in the merged timeline,
    not just as a mysteriously slow step. Compile seconds come from
    jax.monitoring's real compile-phase durations when the runtime emits
    them (this jax does), with the first-call wall time as the fallback
    and always recorded alongside in the event.

On-demand device profiles
    `capture_device_profile(seconds, out_dir)` wraps
    `jax.profiler.start_trace`/`stop_trace` behind a process-wide lock;
    the exporter serves it as `GET /debug/profile?seconds=N` on every
    role, and the master's `StartProfile` RPC fans the HTTP call out to
    every advertised endpoint — so any running role can be profiled
    without a restart, writing into the job's obs dir.

Everything is cheap until it fires: a warm-cache tracked call costs one
shape-key hash and one C++ cache-size read. ELASTICDL_COMPILE_TRACKER=0
degrades tracked_jit to a plain jax.jit.
"""

import json
import os
import threading
import time

from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import events as _events
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.metrics import default_registry

logger = get_logger("observability.profiling")

TRACKER_ENV = "ELASTICDL_COMPILE_TRACKER"
PROFILE_MAX_SECONDS_ENV = "ELASTICDL_PROFILE_MAX_SECONDS"

CAUSE_COLD = "cold"
CAUSE_MESH = "mesh_change"
CAUSE_SHAPE = "shape_change"
CAUSE_REBUILD = "rebuild"
CAUSE_DONATION = "donation_miss"

_REG = default_registry()
_C_COMPILES = _REG.counter(
    "edl_compile_total",
    "Tracked step-function lowerings, by function and attributed cause",
    labelnames=("fn", "cause"),
)
_C_COMPILE_SECONDS = _REG.counter(
    "edl_compile_seconds_total",
    "Seconds spent compiling tracked step functions, by function and "
    "cause (jax.monitoring compile phases when available, else the "
    "first-call wall time)",
    labelnames=("fn", "cause"),
)
_G_LAST_COMPILE = _REG.gauge(
    "edl_compile_last_seconds",
    "Duration of the most recent tracked compile",
)
_C_CACHE_HITS = _REG.counter(
    "edl_compile_cache_hits_total",
    "Tracked lowerings fully served by the persistent compilation "
    "cache (rehydrated executables, by function and the cause the "
    "compile would have had)",
    labelnames=("fn", "cause"),
)

# jax.monitoring event keys that cover a lowering's host-side cost on
# this runtime (trace -> MLIR -> backend compile).
_COMPILE_EVENT_PREFIXES = (
    "/jax/core/compile/",
    "/jax/pjit/",
)

# Persistent-compilation-cache outcome events (common/compile_cache.py
# wires the cache): a lowering whose every backend compile was served
# from disk is a REHYDRATION, not a compile — it lands as a
# `compile_cache_hit` event + edl_compile_cache_hits_total, and does NOT
# count toward edl_compile_total (so "mesh_change stays flat during a
# warm-cache worker-kill drill" is assertable directly on the counter).
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def tracker_enabled():
    return knobs.get_str(TRACKER_ENV).lower() not in ("0", "false", "off")


# ---------------------------------------------------------------------------
# mesh fingerprint
# ---------------------------------------------------------------------------

_mesh_lock = threading.Lock()
_mesh_token = ""
_mesh_world = 0


def note_mesh(token, world_size=0):
    """Stamp the current mesh/world fingerprint. The elastic trainer
    calls this on every world change (token = mesh axes + membership
    epoch), so the next lowering of any tracked function is attributed
    to the regroup instead of reading as a random shape change."""
    global _mesh_token, _mesh_world
    with _mesh_lock:
        _mesh_token = str(token)
        _mesh_world = int(world_size)


def current_mesh():
    with _mesh_lock:
        return _mesh_token, _mesh_world


# ---------------------------------------------------------------------------
# jax.monitoring capture
# ---------------------------------------------------------------------------

_capture = threading.local()  # .sink: list to append (key, secs) into
_listener_installed = False
_listener_lock = threading.Lock()


def _on_event_duration(name, secs, **kw):
    sink = getattr(_capture, "sink", None)
    if sink is None:
        return
    if name.startswith(_COMPILE_EVENT_PREFIXES):
        sink.append((name, float(secs)))


def _on_event(name, **kw):
    sink = getattr(_capture, "events", None)
    if sink is None:
        return
    if name in (_CACHE_HIT_EVENT, _CACHE_MISS_EVENT):
        sink.append(name)


def _install_listener():
    """Register the process-wide jax.monitoring listeners once (lazily,
    so importing this module never imports jax)."""
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return
        try:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                _on_event_duration
            )
            jax.monitoring.register_event_listener(_on_event)
            _listener_installed = True
        except Exception:  # unexpected runtime without monitoring
            _listener_installed = True  # don't retry every call


class _MonitoringCapture:
    """Collects this thread's compile-phase durations (and persistent-
    cache outcome events) around one call."""

    def __enter__(self):
        self._prev = getattr(_capture, "sink", None)
        self._prev_events = getattr(_capture, "events", None)
        self.samples = []
        self.cache_events = []
        _capture.sink = self.samples
        _capture.events = self.cache_events
        return self

    def __exit__(self, *exc):
        _capture.sink = self._prev
        _capture.events = self._prev_events
        return False

    def compile_seconds(self):
        return sum(secs for _, secs in self.samples)

    def persistent_cache_hit(self):
        """True when the persistent compilation cache served EVERY
        backend compile of this call (one jit call can compile several
        subprograms; a single miss means real compile work happened)."""
        hits = self.cache_events.count(_CACHE_HIT_EVENT)
        misses = self.cache_events.count(_CACHE_MISS_EVENT)
        return hits > 0 and misses == 0


# ---------------------------------------------------------------------------
# compile tracker
# ---------------------------------------------------------------------------


class _FnHistory:
    """Process-global per-logical-name compile history (survives wrapper
    rebuilds, which happen on every elastic regroup / restore)."""

    __slots__ = ("compiled_once", "last_mesh_token", "sigs")

    def __init__(self):
        self.compiled_once = False
        self.last_mesh_token = None
        self.sigs = set()  # (mesh_token, shape_sig) ever compiled


class CompileTracker:
    """Counts and times lowerings of tracked functions; process-global."""

    def __init__(self):
        self._lock = threading.Lock()
        self._history = {}  # name -> _FnHistory
        self._events = []  # bounded recent-compile list for reports
        self._events_cap = 256
        self.total_compiles = 0
        self.total_seconds = 0.0
        self.peak_seconds = 0.0  # longest single compile observed
        self.by_cause = {}

    def classify_locked(self, name, sig, mesh_token):
        hist = self._history.get(name)
        if hist is None:
            hist = self._history[name] = _FnHistory()
        if not hist.compiled_once:
            return hist, CAUSE_COLD
        if (mesh_token, sig) in hist.sigs:
            return hist, CAUSE_REBUILD
        if hist.last_mesh_token != mesh_token:
            return hist, CAUSE_MESH
        return hist, CAUSE_SHAPE

    def record(self, name, cause, seconds, wall_seconds, sig=None,
               mesh_token="", cache_hit=False):
        """One observed compile: metrics + event + recent-report entry.
        The trace span is recorded by the caller (it owns the start
        timestamp). `cache_hit=True` means the persistent compilation
        cache rehydrated the executable: the lowering updates the
        classification history (later re-lowerings of the same signature
        still read as rebuilds) but lands as a `compile_cache_hit` event
        and counter instead of a compile — it neither moves
        edl_compile_total nor widens the peak-compile floor timeouts
        derive from."""
        with self._lock:
            hist = self._history.get(name)
            if hist is None:
                hist = self._history[name] = _FnHistory()
            hist.compiled_once = True
            hist.last_mesh_token = mesh_token
            if sig is not None:
                hist.sigs.add((mesh_token, sig))
            entry = {
                "ts": time.time(),
                "fn": name,
                "cause": cause,
                "seconds": round(seconds, 4),
            }
            if cache_hit:
                entry["cache_hit"] = True
            else:
                self.total_compiles += 1
                self.total_seconds += seconds
                self.peak_seconds = max(self.peak_seconds, seconds)
                self.by_cause[cause] = self.by_cause.get(cause, 0) + 1
            self._events.append(entry)
            del self._events[: -self._events_cap]
        world = current_mesh()[1]
        if cache_hit:
            _C_CACHE_HITS.labels(fn=name, cause=cause).inc()
            _events.emit(
                "compile_cache_hit",
                fn=name,
                cause=cause,
                seconds=round(seconds, 4),
                world_size=world,
            )
            return
        _C_COMPILES.labels(fn=name, cause=cause).inc()
        _C_COMPILE_SECONDS.labels(fn=name, cause=cause).inc(seconds)
        _G_LAST_COMPILE.set(seconds)
        _events.emit(
            "compile",
            fn=name,
            cause=cause,
            seconds=round(seconds, 4),
            first_call_seconds=round(wall_seconds, 4),
            world_size=world,
        )

    def snapshot(self):
        """(total_compiles, total_seconds, by_cause) — runner/report
        consumers diff two snapshots to attribute recompile time to one
        window."""
        with self._lock:
            return (
                self.total_compiles,
                self.total_seconds,
                dict(self.by_cause),
            )

    def recent(self, n=32):
        with self._lock:
            return list(self._events[-n:])


_tracker = CompileTracker()


def tracker():
    return _tracker


def peak_compile_seconds():
    """The longest single compile this process has observed (0.0 before
    any). Timeouts that must outlast a peer's recompile — the elastic
    join gate above all — derive their floor from this instead of
    guessing a constant."""
    with _tracker._lock:
        return _tracker.peak_seconds


class TrackedFunction:
    """A jitted callable that reports its own lowerings.

    Forwards the AOT surface (`lower`, `_cache_size`, ...) to the
    underlying jitted function so MFU cost analysis and the benches keep
    working against the wrapped object.
    """

    def __init__(self, jitted, name, key_argnums=None):
        self._jitted = jitted
        self._name = name
        self._key_argnums = key_argnums
        self._seen = set()
        self._expected_cache = 0

    # -- forwarding --

    @property
    def __wrapped__(self):
        return self._jitted

    def __getattr__(self, item):
        return getattr(self.__dict__["_jitted"], item)

    def lower(self, *args, **kw):
        return self._jitted.lower(*args, **kw)

    # -- signature --

    def _sig(self, args, kwargs):
        import jax

        if self._key_argnums is not None:
            args = tuple(args[i] for i in self._key_argnums)
        leaves = jax.tree_util.tree_leaves(args)
        if kwargs:
            # Keyword args (legal on any jitted callable) always join
            # the signature — key_argnums only narrows the positionals.
            leaves += jax.tree_util.tree_leaves(
                tuple(kwargs[k] for k in sorted(kwargs))
            )
        return tuple(
            (
                tuple(getattr(l, "shape", ())),
                str(getattr(l, "dtype", type(l).__name__)),
            )
            for l in leaves
        )

    def _observed_cache_size(self):
        try:
            return int(self._jitted._cache_size())
        except Exception:
            return None

    def __call__(self, *args, **kwargs):
        try:
            sig = self._sig(args, kwargs)
        except Exception:
            return self._jitted(*args, **kwargs)
        mesh_token = current_mesh()[0]
        key = (mesh_token, sig)
        predicted = key not in self._seen
        if not predicted:
            # Warm path: one dict probe + one C++ cache-size read; the
            # cache-size check is what surfaces silent retraces.
            out = self._jitted(*args, **kwargs)
            size = self._observed_cache_size()
            if size is not None and size > self._expected_cache:
                extra = size - self._expected_cache
                self._expected_cache = size
                for _ in range(extra):
                    _tracker.record(
                        self._name, CAUSE_DONATION, 0.0, 0.0,
                        mesh_token=mesh_token,
                    )
            return out
        _install_listener()
        start = time.time()
        t0 = time.perf_counter()
        with _MonitoringCapture() as cap:
            out = self._jitted(*args, **kwargs)
        wall = time.perf_counter() - t0
        self._seen.add(key)
        size = self._observed_cache_size()
        if size is not None:
            if size == self._expected_cache:
                # The underlying cache did not grow: jax already had the
                # executable (cannot happen for a fresh jit object, but a
                # shared one stays honest here) — no compile to record.
                return out
            self._expected_cache = size
        compile_s = cap.compile_seconds() or wall
        cache_hit = cap.persistent_cache_hit()
        with _tracker._lock:
            _, cause = _tracker.classify_locked(
                self._name, sig, mesh_token
            )
        _tracker.record(
            self._name, cause, compile_s, wall, sig=sig,
            mesh_token=mesh_token, cache_hit=cache_hit,
        )
        tracing.record_span(
            f"compile:{self._name}", start, wall, cat="compile",
            args={
                "cause": cause,
                "compile_s": round(compile_s, 4),
                **({"persistent_cache": "hit"} if cache_hit else {}),
            },
        )
        if compile_s > 0.5:
            logger.info(
                "Compiled %s in %.2fs (cause=%s, wall %.2fs)",
                self._name, compile_s, cause, wall,
            )
        return out


def tracked_jit(fn, *, name, key_argnums=None, **jit_kwargs):
    """`jax.jit` with compile accounting. `name` is the logical step
    name the metrics/events carry (stable across rebuilds); `key_argnums`
    restricts the per-call shape signature to the argument positions
    that actually vary (trainers pass the batch so the hot path never
    flattens the parameter tree)."""
    import jax

    jitted = jax.jit(fn, **jit_kwargs)
    if not tracker_enabled():
        return jitted
    return TrackedFunction(jitted, name, key_argnums=key_argnums)


# ---------------------------------------------------------------------------
# on-demand device profiles
# ---------------------------------------------------------------------------

_profile_lock = threading.Lock()


def capture_device_profile(seconds, out_dir):
    """Capture a jax.profiler trace of this process for `seconds` into a
    timestamped subdirectory of `out_dir`. Returns a JSON-able summary
    {dir, files, bytes, seconds}; raises RuntimeError when a capture is
    already running (the profiler is process-global)."""
    import jax.profiler

    import math

    seconds = float(seconds)
    if not math.isfinite(seconds):
        # ?seconds=inf parses as a float; sleeping on it would wedge
        # the process-wide capture lock until restart.
        raise ValueError(f"seconds must be finite, got {seconds!r}")
    cap = knobs.get_float(PROFILE_MAX_SECONDS_ENV)
    seconds = max(0.1, min(seconds, cap) if cap else seconds)
    if not _profile_lock.acquire(blocking=False):
        raise RuntimeError("a device profile capture is already running")
    try:
        stamp = time.strftime("%Y%m%d-%H%M%S")
        target = os.path.join(out_dir, f"profile-{stamp}-{os.getpid()}")
        os.makedirs(target, exist_ok=True)
        _events.emit("profile_start", dir=target, seconds=seconds)
        jax.profiler.start_trace(target)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        files, total = [], 0
        for root, _, names in os.walk(target):
            for n in names:
                p = os.path.join(root, n)
                files.append(os.path.relpath(p, target))
                total += os.path.getsize(p)
        summary = {
            "dir": target,
            "files": sorted(files),
            "bytes": total,
            "seconds": seconds,
        }
        _events.emit(
            "profile_done", dir=target, bytes=total, files=len(files)
        )
        return summary
    finally:
        _profile_lock.release()


def profile_provider(obs_dir, role):
    """The callable observability.setup() hands the exporter for
    /debug/profile: captures into <obs_dir>/profiles/<role>/."""
    base = os.path.join(obs_dir or ".", "profiles", role or "process")

    def provider(seconds):
        return capture_device_profile(seconds, base)

    return provider


def fanout_profiles(endpoints, seconds, timeout_margin=20.0):
    """Hit every advertised endpoint's /debug/profile concurrently
    (the master's StartProfile RPC body). Returns {role: result-dict};
    failures land as {"error": ...} per role, never an exception."""
    import urllib.request

    results = {}
    lock = threading.Lock()

    def one(info):
        role = info.get("role", "?")
        host = info.get("host") or "127.0.0.1"
        url = (
            f"http://{host}:{info['port']}/debug/profile"
            f"?seconds={seconds:g}"
        )
        try:
            body = urllib.request.urlopen(
                url, timeout=seconds + timeout_margin
            ).read()
            out = json.loads(body.decode())
        except Exception as e:
            out = {"error": str(e)[:200]}
        with lock:
            results[role] = out

    threads = [
        threading.Thread(target=one, args=(info,), daemon=True)
        for info in endpoints
        if info.get("port")
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=seconds + timeout_margin + 5)
    return results
