"""Declarative alert rules over the aggregator's job-level signals.

The TelemetryAggregator derives one `signals` dict per scrape
(aggregator.py documents the keys); this engine evaluates a small set of
rules against it and turns rule transitions into durable records:

- an `alert` event in events.jsonl on activation (and `alert_resolved`
  when the condition clears),
- `edl_alerts_total{rule=...}` counter increments,
- an `edl_alerts_active{rule=...}` gauge while the condition holds,
- an `active()` snapshot consumed by /api/summary, `edl dash`, and the
  straggler field of JobStatusResponse.

Three rule kinds cover the anomaly classes the ISSUE drills:

  threshold  a scalar signal crossed a bound (tasks abandoned, ...)
  skew       one subject of a {subject: score} map diverges from the
             fleet (stragglers, PS shard load imbalance; scores are
             value/median, computed by the aggregator)
  stall      a progress counter stopped moving for too long while the
             job still claims in-flight work

Alerts fire on the RISING edge only — a straggler that stays slow is one
alert, not one per scrape — and re-arm after the condition clears.

Tuning (all optional):
  ELASTICDL_ALERT_STRAGGLER_SKEW  flag workers slower than this multiple
                                  of the fleet median step time (def 2.0)
  ELASTICDL_ALERT_PS_SKEW         flag PS shards above this multiple of
                                  the mean byte rate (def 3.0)
  ELASTICDL_ALERT_STALL_SECONDS   records_done frozen this long with
                                  tasks in flight -> stall (def 60)
  ELASTICDL_ALERT_ABANDONED       abandoned-task count threshold (def 1)
  ELASTICDL_ALERT_STARVE_SHARE    flag workers whose step sat on an
                                  empty feed queue more than this
                                  fraction of wall time (def 0.25)
"""

import threading
import time

from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import emit_event
from elasticdl_tpu.observability.metrics import default_registry

logger = get_logger("observability.alerts")

STRAGGLER_SKEW_ENV = "ELASTICDL_ALERT_STRAGGLER_SKEW"
PS_SKEW_ENV = "ELASTICDL_ALERT_PS_SKEW"
STALL_SECONDS_ENV = "ELASTICDL_ALERT_STALL_SECONDS"
ABANDONED_ENV = "ELASTICDL_ALERT_ABANDONED"
STARVE_SHARE_ENV = "ELASTICDL_ALERT_STARVE_SHARE"

class Rule:
    """One named condition; evaluate() returns {subject: detail_dict} for
    every subject currently violating it (empty dict = all clear)."""

    def __init__(self, name):
        self.name = name

    def evaluate(self, signals, now):
        raise NotImplementedError


class ThresholdRule(Rule):
    def __init__(self, name, signal, threshold):
        super().__init__(name)
        self.signal = signal
        self.threshold = threshold

    def evaluate(self, signals, now):
        value = signals.get(self.signal)
        if value is None or value < self.threshold:
            return {}
        return {
            self.signal: {"value": value, "threshold": self.threshold}
        }


class SkewRule(Rule):
    """Fires per subject whose precomputed skew score (value / fleet
    median or mean — the aggregator owns the normalization) crosses the
    threshold."""

    def __init__(self, name, signal, threshold):
        super().__init__(name)
        self.signal = signal
        self.threshold = threshold

    def evaluate(self, signals, now):
        scores = signals.get(self.signal) or {}
        return {
            subject: {"score": round(score, 3),
                      "threshold": self.threshold}
            for subject, score in scores.items()
            if score >= self.threshold
        }


class StallRule(Rule):
    """A progress signal (monotonic counter, e.g. records_done) that has
    not advanced for `seconds` while the gate signal is truthy (work is
    supposedly in flight). Carries state across evaluations."""

    def __init__(self, name, progress, gate, seconds):
        super().__init__(name)
        self.progress = progress
        self.gate = gate
        self.seconds = seconds
        self._last_value = None
        self._last_advance = None

    def evaluate(self, signals, now):
        value = signals.get(self.progress)
        if value is None:
            return {}
        if self._last_value is None or value != self._last_value:
            self._last_value = value
            self._last_advance = now
            return {}
        if not signals.get(self.gate):
            # Nothing in flight: an idle queue is not a stall.
            self._last_advance = now
            return {}
        stalled_for = now - self._last_advance
        if stalled_for < self.seconds:
            return {}
        return {
            self.progress: {
                "stalled_seconds": round(stalled_for, 1),
                "value": value,
                "threshold_seconds": self.seconds,
            }
        }


def straggler_skew_threshold():
    return knobs.get_float(STRAGGLER_SKEW_ENV)


def default_rules():
    """The stock rule set, thresholds from the environment."""
    return [
        SkewRule(
            "straggler", "straggler_scores", straggler_skew_threshold()
        ),
        SkewRule(
            "ps_imbalance",
            "ps_skew_scores",
            knobs.get_float(PS_SKEW_ENV),
        ),
        ThresholdRule(
            "tasks_abandoned",
            "tasks_abandoned",
            knobs.get_float(ABANDONED_ENV),
        ),
        StallRule(
            "throughput_stall",
            progress="records_done",
            gate="tasks_doing",
            seconds=knobs.get_float(STALL_SECONDS_ENV),
        ),
        # input_starve_shares are ABSOLUTE fractions of wall time (the
        # aggregator owns the normalization, per the SkewRule contract),
        # so the threshold compares against the share itself rather
        # than a fleet median — starvation on every worker at once is
        # still an incident.
        SkewRule(
            "input_starvation",
            "input_starve_shares",
            knobs.get_float(STARVE_SHARE_ENV),
        ),
    ]


class AlertEngine:
    """Evaluates rules each scrape; edge-triggered emission + active set.

    evaluate() runs on the aggregator's single scrape thread; active()
    snapshots are read from gRPC handler threads, so the active set is
    lock-guarded.
    """

    def __init__(self, rules=None, registry=None):
        self.rules = default_rules() if rules is None else list(rules)
        reg = registry or default_registry()
        self._fired = reg.counter(
            "edl_alerts_total",
            "Alert rule activations (rising edge), by rule",
            labelnames=("rule",),
        )
        self._active_gauge = reg.gauge(
            "edl_alerts_active",
            "Alert conditions currently holding, by rule",
            labelnames=("rule",),
        )
        self._lock = threading.Lock()
        self._active = {}  # (rule, subject) -> detail dict
        self.fired_total = 0

    def evaluate(self, signals, now=None):
        """Run every rule; returns the list of NEWLY fired alerts as
        {"rule", "subject", ...detail} dicts."""
        now = time.time() if now is None else now
        fired = []
        resolved = []
        seen = set()
        with self._lock:
            for rule in self.rules:
                try:
                    violations = rule.evaluate(signals, now)
                except Exception:
                    logger.warning(
                        "Alert rule %s failed to evaluate", rule.name,
                        exc_info=True,
                    )
                    continue
                for subject, detail in violations.items():
                    key = (rule.name, subject)
                    seen.add(key)
                    if key in self._active:
                        self._active[key] = detail
                        continue
                    self._active[key] = detail
                    self.fired_total += 1
                    self._fired.labels(rule=rule.name).inc()
                    fired.append(
                        {"rule": rule.name, "subject": subject, **detail}
                    )
            for key in list(self._active):
                if key not in seen:
                    rule_name, subject = key
                    del self._active[key]
                    resolved.append((rule_name, subject))
            counts = {}
            for rule_name, _ in self._active:
                counts[rule_name] = counts.get(rule_name, 0) + 1
            for rule in self.rules:
                self._active_gauge.labels(rule=rule.name).set(
                    counts.get(rule.name, 0)
                )
        # Event-log appends happen OUTSIDE the lock: get_job_status reads
        # active_subjects() under it, and a slow obs-dir mount must not
        # stall the very RPCs reporting the incident.
        for record in fired:
            emit_event("alert", **record)
            logger.warning(
                "ALERT %s: %s %s",
                record["rule"],
                record["subject"],
                {
                    k: v
                    for k, v in record.items()
                    if k not in ("rule", "subject")
                },
            )
        for rule_name, subject in resolved:
            emit_event("alert_resolved", rule=rule_name, subject=subject)
        return fired

    def active(self):
        """Currently-holding alerts, most useful fields first."""
        with self._lock:
            return [
                {"rule": rule, "subject": subject, **detail}
                for (rule, subject), detail in sorted(
                    self._active.items()
                )
            ]

    def active_subjects(self, rule_name):
        with self._lock:
            return sorted(
                subject
                for (rule, subject) in self._active
                if rule == rule_name
            )
