"""Crash-dump flight recorder: the last N spans, dumped when we die.

A bounded in-memory ring of recent step-phase spans and RPC timings per
role, written to ``<dir>/flightrec-<role>.json`` when the process
crashes (unhandled exception), receives SIGTERM, or a bench watchdog
gives up on it — so a dead bench or drill leaves attributable evidence
("died 41 s into ps_matrix:ps2-overlapped-bf16, last event a
push_gradients wire wait") instead of an rc=124 and an empty log tail.

Design constraints:

- ALWAYS CHEAP: recording is an append to a ``deque(maxlen=N)`` under a
  lock; nothing is written to disk until a dump trigger fires. The
  recorder feeds off the tracing plane (``tracing.add_sink``) so every
  span the PR1 instrumentation already emits — step phases, RPC
  client/server spans, the push serialize/wire/apply sub-spans — lands
  in the ring with no second instrumentation pass.
- NAMES THE PHASE IT DIED IN: ``phase()`` tracks a per-thread stack of
  OPEN phases (entered, not yet exited). A span only reaches the ring
  when it *closes*; the open-phase stack is what says where execution
  currently is — exactly the thing a timeout needs attributed.
- TRIGGER-SAFE: the dump path builds the JSON from plain dicts and
  writes atomically (tmp + rename); signal handlers chain to whatever
  handler was installed before, and the excepthook chains to the
  previous hook, so arming the recorder never changes process
  semantics.

Knobs: ELASTICDL_FLIGHTREC (auto/1/0), ELASTICDL_FLIGHTREC_CAPACITY,
ELASTICDL_FLIGHTREC_DIR (falls back to ELASTICDL_OBS_DIR, then cwd).
"""

import collections
import contextlib
import json
import os
import signal
import sys
import threading
import time

from elasticdl_tpu.common import knobs
from elasticdl_tpu.observability import tracing

_recorder = None
_prev_excepthook = None
_prev_handlers = {}

# Signals that mean "you are being killed, leave evidence". SIGTERM is
# what k8s, the bench driver's `timeout`, and drills send.
_SIGNALS = (signal.SIGTERM,)


class FlightRecorder:
    """Bounded ring of recent spans + open-phase tracking for one role."""

    def __init__(self, role, capacity, dump_dir):
        self.role = role
        self.dump_dir = dump_dir
        # RLock, not Lock: the SIGTERM handler dumps via snapshot(),
        # and Python delivers signals on the MAIN thread at bytecode
        # boundaries — including inside on_span()/phase()'s critical
        # sections. With a plain Lock the handler would self-deadlock
        # trying to re-acquire a lock its own (interrupted) thread
        # holds, and the process would neither dump nor die. Reentrancy
        # means the dump may read a snapshot mid-mutation (at worst one
        # event torn/missing) — the right trade for crash tooling.
        # Another thread holding the lock only delays the handler by
        # one tiny append, never deadlocks it.
        self._lock = threading.RLock()
        self._events = collections.deque(maxlen=capacity)
        self._rpc = {}
        self._open = {}
        self._started = time.time()
        self._dumps = 0

    # ---------- recording ----------

    def on_span(self, name, start_s, dur_s, cat, args):
        """tracing sink: one CLOSED span."""
        event = {
            "ts": round(start_s, 3),
            "name": name,
            "cat": cat,
            "dur_ms": round(dur_s * 1e3, 2),
        }
        if args:
            # Keep only scalar args: the ring must stay tiny and
            # JSON-serializable no matter what a caller attached.
            scalars = {
                k: v
                for k, v in args.items()
                if isinstance(v, (str, int, float, bool))
            }
            if scalars:
                event["args"] = scalars
        with self._lock:
            self._events.append(event)
            if cat == "rpc":
                agg = self._rpc.get(name)
                if agg is None:
                    agg = self._rpc[name] = [0, 0.0]
                agg[0] += 1
                agg[1] += dur_s

    @contextlib.contextmanager
    def phase(self, name):
        """Track an OPEN phase on this thread; the dump names every
        phase still open at trigger time, innermost last."""
        ident = threading.get_ident()
        entry = (name, time.time())
        with self._lock:
            self._open.setdefault(ident, []).append(entry)
        try:
            yield
        finally:
            closed = time.time() - entry[1]
            with self._lock:
                stack = self._open.get(ident)
                if stack and stack[-1] is entry:
                    stack.pop()
                if not stack:
                    self._open.pop(ident, None)
            self.on_span(entry[0], entry[1], closed, "phase", None)

    # ---------- dumping ----------

    def snapshot(self, reason):
        now = time.time()
        with self._lock:
            open_phases = [
                {
                    "name": name,
                    "age_s": round(now - start, 3),
                    "thread": ident,
                }
                for ident, stack in self._open.items()
                for name, start in stack
            ]
            events = list(self._events)
            rpc = {
                method: {
                    "count": count,
                    "total_ms": round(total_s * 1e3, 2),
                    "mean_ms": round(total_s * 1e3 / max(count, 1), 2),
                }
                for method, (count, total_s) in self._rpc.items()
            }
        # Innermost (most recent) open phase last: the phase it died in.
        open_phases.sort(key=lambda p: -p["age_s"])
        return {
            "role": self.role,
            "reason": reason,
            "ts": now,
            "uptime_s": round(now - self._started, 3),
            "open_phases": open_phases,
            "rpc": rpc,
            "events": events,
        }

    def dump(self, reason):
        """Write the ring to flightrec-<role>.json (atomic). Returns the
        path. Never raises — this runs from signal handlers and
        excepthooks, where a secondary failure would mask the primary."""
        try:
            snap = self.snapshot(reason)
            with self._lock:
                self._dumps += 1
                snap["dump_seq"] = self._dumps
            os.makedirs(self.dump_dir or ".", exist_ok=True)
            path = os.path.join(
                self.dump_dir or ".", f"flightrec-{self.role}.json"
            )
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(snap, f, indent=1)
            os.replace(tmp, path)
            return path
        except Exception:
            return None


# ---------------------------------------------------------------------------
# Module-level lifecycle: one recorder per process, armed triggers.
# ---------------------------------------------------------------------------


def get():
    return _recorder


def _resolve_dir(dump_dir):
    if dump_dir:
        return dump_dir
    configured = knobs.get_str("ELASTICDL_FLIGHTREC_DIR")
    if configured:
        return configured
    obs_dir = knobs.get_str("ELASTICDL_OBS_DIR")
    return obs_dir or "."


def install(role, capacity=None, dump_dir=None, arm_signals=True):
    """Arm the flight recorder for this process (idempotent; returns the
    recorder, or None when ELASTICDL_FLIGHTREC disables it)."""
    global _recorder, _prev_excepthook
    if _recorder is not None:
        return _recorder
    enabled = knobs.get_str("ELASTICDL_FLIGHTREC").strip().lower()
    if enabled in ("0", "false", "off"):
        return None
    if capacity is None:
        capacity = knobs.get_int("ELASTICDL_FLIGHTREC_CAPACITY")
    recorder = FlightRecorder(
        role, max(capacity, 8), _resolve_dir(dump_dir)
    )
    _recorder = recorder
    tracing.add_sink(recorder.on_span)
    _prev_excepthook = sys.excepthook
    sys.excepthook = _crash_hook
    if arm_signals:
        for sig in _SIGNALS:
            try:
                _prev_handlers[sig] = signal.signal(sig, _signal_hook)
            except ValueError:
                # Not the main thread: signal triggers stay with whoever
                # owns them; explicit dump()/excepthook still work.
                pass
    return recorder


def uninstall():
    """Disarm (tests): remove the sink, restore hooks and handlers."""
    global _recorder, _prev_excepthook
    if _recorder is None:
        return
    tracing.remove_sink(_recorder.on_span)
    if sys.excepthook is _crash_hook and _prev_excepthook is not None:
        sys.excepthook = _prev_excepthook
    _prev_excepthook = None
    for sig, prev in list(_prev_handlers.items()):
        try:
            if signal.getsignal(sig) is _signal_hook:
                signal.signal(sig, prev)
        except ValueError:
            pass
        _prev_handlers.pop(sig, None)
    _recorder = None


def dump(reason):
    """Dump now (e.g. a watchdog naming the benchmark it abandoned).
    Returns the dump path, or None when no recorder is armed."""
    if _recorder is None:
        return None
    return _recorder.dump(reason)


def phase(name):
    """Context manager marking an open phase; no-op when not armed."""
    if _recorder is None:
        return contextlib.nullcontext()
    return _recorder.phase(name)


def _crash_hook(exc_type, exc, tb):
    if _recorder is not None:
        _recorder.dump(f"crash:{exc_type.__name__}")
    if _prev_excepthook is not None:
        _prev_excepthook(exc_type, exc, tb)


def _signal_hook(signum, frame):
    if _recorder is not None:
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        _recorder.dump(f"signal:{name}")
    prev = _prev_handlers.get(signum)
    if callable(prev):
        prev(signum, frame)
        return
    # Default/ignored before: restore and re-raise so the process dies
    # with the right wait status (k8s and the drills read it).
    signal.signal(signum, prev if prev is not None else signal.SIG_DFL)
    os.kill(os.getpid(), signum)
