"""Prometheus text-exposition parser — the exact inverse of
`MetricsRegistry.expose()`.

The aggregator (observability/aggregator.py) scrapes every per-role
`/metrics` endpoint of a job and needs the samples back as structured data;
this module parses the plain-text v0.0.4 format with stdlib only, the same
zero-dependency stance as the writer side (metrics.py).

Contract with the writer: `parse(registry.expose())` yields one
`MetricFamily` per registered metric, each carrying the samples the
registry holds, and `to_text(parse(text)) == text` for any text the
registry emits (families stay in input order, values re-format through the
writer's own number formatter). Histogram families own their `_bucket` /
`_sum` / `_count` sample lines.
"""

import collections
import re

from elasticdl_tpu.observability.metrics import _format_value

# One exposition sample: the sample's full name (family name, or
# family name + _bucket/_sum/_count for histograms), its labels as an
# ordered (name, value) tuple, and the float value.
Sample = collections.namedtuple("Sample", ("name", "labels", "value"))

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_LABEL_RE = re.compile(
    r'\s*([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"\s*(,)?'
)

# Sample-name suffixes a histogram family owns.
_HISTOGRAM_SUFFIXES = ("_bucket", "_sum", "_count")


class MetricFamily:
    def __init__(self, name, type="untyped", help=""):
        self.name = name
        self.type = type
        self.help = help
        self.samples = []

    def __repr__(self):
        return (
            f"MetricFamily({self.name!r}, type={self.type!r}, "
            f"samples={len(self.samples)})"
        )


class ParseError(ValueError):
    pass


def _unescape_label_value(value):
    # Inverse of metrics._format_labels: \\ -> \, \" -> ", \n -> newline.
    out = []
    i = 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "\\":
                out.append("\\")
            elif nxt == '"':
                out.append('"')
            elif nxt == "n":
                out.append("\n")
            else:
                out.append(ch)
                out.append(nxt)
            i += 2
            continue
        out.append(ch)
        i += 1
    return "".join(out)


def _parse_labels(text):
    """'k="v",k2="v2"' (brace contents) -> ordered ((k, v), ...)."""
    labels = []
    pos = 0
    while pos < len(text):
        m = _LABEL_RE.match(text, pos)
        if m is None:
            raise ParseError(f"bad label syntax at {text[pos:]!r}")
        labels.append((m.group(1), _unescape_label_value(m.group(2))))
        pos = m.end()
    return tuple(labels)


def _parse_sample(line):
    m = _NAME_RE.match(line)
    if m is None:
        raise ParseError(f"bad sample line {line!r}")
    name = m.group(0)
    rest = line[m.end():]
    labels = ()
    if rest.startswith("{"):
        close = _find_brace_close(rest)
        labels = _parse_labels(rest[1:close])
        rest = rest[close + 1:]
    value_text = rest.strip()
    if not value_text:
        raise ParseError(f"sample {name!r} has no value")
    try:
        value = float(value_text)
    except ValueError as e:
        raise ParseError(f"bad value {value_text!r} for {name!r}") from e
    return Sample(name, labels, value)


def _find_brace_close(text):
    """Index of the '}' closing text's leading '{', skipping quoted label
    values (a '}' inside a label value must not terminate the block)."""
    in_quotes = False
    i = 1
    while i < len(text):
        ch = text[i]
        if in_quotes:
            if ch == "\\":
                i += 2
                continue
            if ch == '"':
                in_quotes = False
        elif ch == '"':
            in_quotes = True
        elif ch == "}":
            return i
        i += 1
    raise ParseError(f"unterminated label block in {text!r}")


def _family_for(families, order, sample_name):
    """The family owning a sample line; histogram suffixes resolve to the
    base family. Samples without HELP/TYPE get an implicit untyped family
    (the format allows them; the registry never emits them)."""
    if sample_name in families:
        return families[sample_name]
    for suffix in _HISTOGRAM_SUFFIXES:
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            fam = families.get(base)
            if fam is not None and fam.type == "histogram":
                return fam
    fam = MetricFamily(sample_name)
    families[sample_name] = fam
    order.append(sample_name)
    return fam


def parse(text):
    """Exposition text -> ordered {family_name: MetricFamily}."""
    families = {}
    order = []
    for raw in text.splitlines():
        line = raw.rstrip("\r")
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                name = parts[2]
                if name not in families:
                    families[name] = MetricFamily(name)
                    order.append(name)
                if parts[1] == "HELP":
                    families[name].help = parts[3] if len(parts) > 3 else ""
                else:
                    families[name].type = (
                        parts[3].strip() if len(parts) > 3 else "untyped"
                    )
            continue  # other comments are legal and ignored
        sample = _parse_sample(line)
        _family_for(families, order, sample.name).samples.append(sample)
    return collections.OrderedDict(
        (name, families[name]) for name in order
    )


def samples(text):
    """Flat [(name, {label: value}, value)] view of `parse(text)`."""
    out = []
    for family in parse(text).values():
        for s in family.samples:
            out.append((s.name, dict(s.labels), s.value))
    return out


def _format_label_block(labels):
    if not labels:
        return ""
    parts = []
    for name, value in labels:
        escaped = (
            str(value)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )
        parts.append(f'{name}="{escaped}"')
    return "{" + ",".join(parts) + "}"


def to_text(families):
    """Families -> exposition text (`to_text(parse(t)) == t` for registry
    output — the round-trip property test's anchor)."""
    lines = []
    for family in families.values():
        lines.append(f"# HELP {family.name} {family.help}")
        lines.append(f"# TYPE {family.name} {family.type}")
        for s in family.samples:
            labels = _format_label_block(s.labels)
            lines.append(f"{s.name}{labels} {_format_value(s.value)}")
    return "\n".join(lines) + "\n"


def sample_value(families, name, labels=None):
    """The value of one sample (labels as a dict subset match), or None."""
    want = dict(labels or {})
    for family in families.values():
        for s in family.samples:
            if s.name != name:
                continue
            have = dict(s.labels)
            if all(have.get(k) == v for k, v in want.items()):
                return s.value
    return None
