"""Structured elasticity event log: events.jsonl alongside metrics.jsonl.

One JSON object per line:

    {"ts": 1722700000.1, "seq": 7, "kind": "pod_relaunch", "job": "j",
     "role": "master", "kind_id": "worker-1", "attempt": 2, ...}

`seq` is a per-process monotonic counter so the job's elasticity timeline
(launch -> exit -> relaunch, lease grant -> abort, task create -> timeout ->
reassign) can be reconstructed in exact order even when two events land
within one clock tick. Emission is a no-op until observability.setup()
installs a log, so library code can emit unconditionally.

Event kinds (docs/OBSERVABILITY.md#event-schema):
  pod_launch / pod_exit / pod_relaunch / pod_failed
  lease_mint / lease_grant / lease_report / lease_abort / lease_complete
  task_create / task_timeout / task_reassign / task_failed / job_failed
  worker_removed / membership_epoch
  compile / mem_high_watermark / profile_start / profile_done / rotated
"""

import json
import threading
import time

from elasticdl_tpu.observability.rotation import SizeCappedFile


class EventLog:
    def __init__(self, path, job="", role="", max_bytes=None):
        self.path = path
        self._job = job
        self._role = role
        self._lock = threading.Lock()
        self._seq = 0
        # Size-capped: the previous generation survives as <path>.1 and
        # every fresh generation opens with a `rotated` marker event so
        # readers see a deliberate cut, not a gap.
        self._file = SizeCappedFile(
            path, max_bytes=max_bytes, on_rotate=self._write_rotated_marker_locked
        )

    def _write_rotated_marker_locked(self, generation):
        # Called under self._lock, mid-write, right after the rename:
        # this marker is the new file's first record.
        self._seq += 1
        self._file.append_line(
            json.dumps(
                {
                    "ts": time.time(),
                    "kind": "rotated",
                    "role": self._role,
                    "generation": generation,
                    "seq": self._seq,
                },
                separators=(",", ":"),
            )
        )

    def emit(self, kind, **fields):
        record = {"ts": time.time(), "kind": kind}
        if self._job:
            record["job"] = self._job
        if self._role:
            record["role"] = self._role
        record.update(fields)
        with self._lock:
            if self._file.closed:
                return
            # Rotation check BEFORE assigning seq: a rotation writes the
            # marker (which takes the next seq) as the new generation's
            # first record, so seq stays monotonic in file order. The
            # +24 covers the seq field this record is about to gain.
            probe = json.dumps(record, separators=(",", ":"))
            self._file.maybe_rotate(len(probe) + 24)
            self._seq += 1
            record["seq"] = self._seq
            self._file.append_line(
                json.dumps(record, separators=(",", ":"))
            )

    def close(self):
        with self._lock:
            if not self._file.closed:
                self._file.close()


_event_log = None


def set_event_log(log):
    global _event_log
    _event_log = log


def get_event_log():
    return _event_log


def emit(kind, **fields):
    """Append one event; silently dropped until a log is configured."""
    log = _event_log
    if log is not None:
        log.emit(kind, **fields)


def read_events(path):
    """Parse an events.jsonl (merge helper for tools/tests). A torn final
    line — the writer was SIGKILLed mid-record, the very scenario this log
    diagnoses — yields the valid prefix instead of raising."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return events
