"""Structured elasticity event log: events.jsonl alongside metrics.jsonl.

One JSON object per line:

    {"ts": 1722700000.1, "seq": 7, "kind": "pod_relaunch", "job": "j",
     "role": "master", "kind_id": "worker-1", "attempt": 2, ...}

`seq` is a per-process monotonic counter so the job's elasticity timeline
(launch -> exit -> relaunch, lease grant -> abort, task create -> timeout ->
reassign) can be reconstructed in exact order even when two events land
within one clock tick. Emission is a no-op until observability.setup()
installs a log, so library code can emit unconditionally.

Event kinds (docs/OBSERVABILITY.md#event-schema):
  pod_launch / pod_exit / pod_relaunch / pod_failed
  lease_mint / lease_grant / lease_report / lease_abort / lease_complete
  task_create / task_timeout / task_reassign / task_failed / job_failed
  worker_removed / membership_epoch
  compile / mem_high_watermark / profile_start / profile_done / rotated
"""

import json
import threading
import time

from elasticdl_tpu.common import knobs
from elasticdl_tpu.observability.metrics import default_registry
from elasticdl_tpu.observability.rotation import SizeCappedFile

COALESCE_SECONDS_ENV = "ELASTICDL_EVENT_COALESCE_SECONDS"
COALESCE_KINDS_ENV = "ELASTICDL_EVENT_COALESCE_KINDS"


class EventLog:
    def __init__(self, path, job="", role="", max_bytes=None,
                 coalesce_seconds=None, coalesce_kinds=None):
        self.path = path
        self._job = job
        self._role = role
        self._lock = threading.Lock()
        self._seq = 0
        # Coalescing window for high-frequency kinds (500-pod churn makes
        # membership_epoch a write-amplification hazard): the first event
        # of a windowed kind writes immediately; later ones inside the
        # window fold into the NEXT write, which carries coalesced=N and
        # the latest fields. Trailing loss — a suppressed event with no
        # successor before close — is accepted and bounded to one window.
        if coalesce_seconds is None:
            coalesce_seconds = knobs.get_float(COALESCE_SECONDS_ENV)
        if coalesce_kinds is None:
            coalesce_kinds = knobs.get_str(COALESCE_KINDS_ENV)
        if isinstance(coalesce_kinds, str):
            coalesce_kinds = {
                k.strip() for k in coalesce_kinds.split(",") if k.strip()
            }
        self._coalesce_seconds = max(0.0, float(coalesce_seconds))
        self._coalesce_kinds = frozenset(coalesce_kinds)
        self._coalesce_state = {}  # kind -> {"last_write", "suppressed"}
        reg = default_registry()
        self._c_written = reg.counter(
            "edl_events_written_total",
            "Event-log records actually written (rotation markers "
            "included)",
        )
        self._c_bytes = reg.counter(
            "edl_events_bytes_total",
            "Bytes appended to events.jsonl",
        )
        self._c_suppressed = reg.counter(
            "edl_events_suppressed_total",
            "Events folded into a later record by the coalescing window",
            labelnames=("kind",),
        )
        # Size-capped: the previous generation survives as <path>.1 and
        # every fresh generation opens with a `rotated` marker event so
        # readers see a deliberate cut, not a gap.
        self._file = SizeCappedFile(
            path, max_bytes=max_bytes, on_rotate=self._write_rotated_marker_locked
        )

    def _write_rotated_marker_locked(self, generation):
        # Called under self._lock, mid-write, right after the rename:
        # this marker is the new file's first record.
        self._seq += 1
        line = json.dumps(
            {
                "ts": time.time(),
                "kind": "rotated",
                "role": self._role,
                "generation": generation,
                "seq": self._seq,
            },
            separators=(",", ":"),
        )
        self._file.append_line(line)
        self._c_written.inc()
        self._c_bytes.inc(len(line) + 1)

    def emit(self, kind, **fields):
        now = time.time()
        record = {"ts": now, "kind": kind}
        if self._job:
            record["job"] = self._job
        if self._role:
            record["role"] = self._role
        record.update(fields)
        with self._lock:
            if self._file.closed:
                return
            if self._coalesce_seconds and kind in self._coalesce_kinds:
                state = self._coalesce_state.setdefault(
                    kind, {"last_write": 0.0, "suppressed": 0}
                )
                if now - state["last_write"] < self._coalesce_seconds:
                    state["suppressed"] += 1
                    self._c_suppressed.labels(kind=kind).inc()
                    return
                if state["suppressed"]:
                    record["coalesced"] = state["suppressed"]
                    state["suppressed"] = 0
                state["last_write"] = now
            # Rotation check BEFORE assigning seq: a rotation writes the
            # marker (which takes the next seq) as the new generation's
            # first record, so seq stays monotonic in file order. The
            # +24 covers the seq field this record is about to gain.
            probe = json.dumps(record, separators=(",", ":"))
            self._file.maybe_rotate(len(probe) + 24)
            self._seq += 1
            record["seq"] = self._seq
            line = json.dumps(record, separators=(",", ":"))
            self._file.append_line(line)
            self._c_written.inc()
            self._c_bytes.inc(len(line) + 1)

    def close(self):
        with self._lock:
            if not self._file.closed:
                self._file.close()


_event_log = None


def set_event_log(log):
    global _event_log
    _event_log = log


def get_event_log():
    return _event_log


def emit(kind, **fields):
    """Append one event; silently dropped until a log is configured."""
    log = _event_log
    if log is not None:
        log.emit(kind, **fields)


def read_events(path):
    """Parse an events.jsonl (merge helper for tools/tests). A torn final
    line — the writer was SIGKILLed mid-record, the very scenario this log
    diagnoses — yields the valid prefix instead of raising."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return events
