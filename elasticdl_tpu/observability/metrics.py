"""Process-local metrics registry with Prometheus text exposition.

Counter / Gauge / Histogram over plain dicts and one lock per metric —
enough for control-plane rates (RPCs, tasks, bytes) without pulling in
prometheus_client. The exposition format is the plain-text v0.0.4 format
every Prometheus scraper speaks:

    # HELP edl_tasks_dispatched_total Tasks handed to workers
    # TYPE edl_tasks_dispatched_total counter
    edl_tasks_dispatched_total{type="TRAINING"} 42

Naming scheme (docs/OBSERVABILITY.md): every metric starts with `edl_`,
counters end in `_total`, durations are `_seconds`, sizes `_bytes`.
Histograms keep BOUNDED state: fixed buckets plus a bounded reservoir so
`quantile()` can answer p50/p99 without unbounded sample growth.
"""

import random
import threading

# Latency-shaped default: 1ms .. ~100s, roughly x4 per step.
DEFAULT_BUCKETS = (
    0.001, 0.004, 0.016, 0.064, 0.25, 1.0, 4.0, 16.0, 64.0,
)

_RESERVOIR_SIZE = 512


class Reservoir:
    """Bounded Algorithm-R sample reservoir with index-based quantiles.
    NOT thread-safe on its own — holders guard it with their own lock
    (one estimator shared by the Histogram metric and common/timing.py,
    so p50/p99 agree between /metrics and the DEBUG timing reports)."""

    def __init__(self, size, seed=0x5EED):
        self.size = size
        self._samples = []
        self._seen = 0
        self._rng = random.Random(seed)

    def add(self, value):
        self._seen += 1
        if len(self._samples) < self.size:
            self._samples.append(value)
        else:
            j = self._rng.randrange(self._seen)
            if j < self.size:
                self._samples[j] = value

    def snapshot(self):
        return list(self._samples)

    @staticmethod
    def quantile_of(ordered, q):
        """Index-based quantile of a pre-sorted sample list."""
        if not ordered:
            return None
        return ordered[min(len(ordered) - 1, int(q * len(ordered)))]

    def quantile(self, q):
        return self.quantile_of(sorted(self._samples), q)


def _format_value(v):
    if v == int(v):
        return str(int(v))
    return repr(float(v))


def _format_labels(labelnames, labelvalues):
    if not labelnames:
        return ""
    parts = []
    for name, value in zip(labelnames, labelvalues):
        value = (
            str(value)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )
        parts.append(f'{name}="{value}"')
    return "{" + ",".join(parts) + "}"


class _Child:
    """One labeled time series of a metric."""

    def __init__(self, parent, labelvalues):
        self._parent = parent
        self._labelvalues = labelvalues
        self._lock = threading.Lock()
        self._value = 0.0
        if parent.type == "histogram":
            self._bucket_counts = [0] * len(parent.buckets)
            self._count = 0
            self._sum = 0.0
            self._reservoir = Reservoir(_RESERVOIR_SIZE)

    # -- counter / gauge --

    def inc(self, amount=1):
        if self._parent.type == "counter" and amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self._value += amount

    def dec(self, amount=1):
        if self._parent.type != "gauge":
            raise ValueError("only gauges can decrease")
        with self._lock:
            self._value -= amount

    def set(self, value):
        if self._parent.type != "gauge":
            raise ValueError("only gauges can be set")
        with self._lock:
            self._value = float(value)

    @property
    def value(self):
        with self._lock:
            return self._value

    # -- histogram --

    def observe(self, value):
        if self._parent.type != "histogram":
            raise ValueError("observe() is histogram-only")
        value = float(value)
        with self._lock:
            self._count += 1
            self._sum += value
            # Per-bucket counts; exposition cumulates them (le semantics).
            for i, bound in enumerate(self._parent.buckets):
                if value <= bound:
                    self._bucket_counts[i] += 1
                    break
            self._reservoir.add(value)

    def quantile(self, q):
        """Reservoir-estimated quantile in [0, 1]; None when empty."""
        with self._lock:
            return self._reservoir.quantile(q)

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def _expose(self, lines):
        name = self._parent.name
        labelnames = self._parent.labelnames
        if self._parent.type == "histogram":
            with self._lock:
                bucket_counts = list(self._bucket_counts)
                count, total = self._count, self._sum
            cumulative = 0
            for bound, n in zip(self._parent.buckets, bucket_counts):
                cumulative += n
                labels = _format_labels(
                    labelnames + ("le",),
                    self._labelvalues + (_format_value(bound),),
                )
                lines.append(f"{name}_bucket{labels} {cumulative}")
            labels = _format_labels(
                labelnames + ("le",), self._labelvalues + ("+Inf",)
            )
            lines.append(f"{name}_bucket{labels} {count}")
            labels = _format_labels(labelnames, self._labelvalues)
            lines.append(f"{name}_sum{labels} {_format_value(total)}")
            lines.append(f"{name}_count{labels} {count}")
        else:
            labels = _format_labels(labelnames, self._labelvalues)
            lines.append(f"{name}{labels} {_format_value(self.value)}")


class Metric:
    """A named metric family; with labelnames it fans out via labels()."""

    def __init__(self, name, help, type, labelnames=(), buckets=None):
        self.name = name
        self.help = help
        self.type = type
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._lock = threading.Lock()
        self._children = {}
        self._default = None if self.labelnames else _Child(self, ())

    def labels(self, *labelvalues, **labelkw):
        if labelkw:
            if labelvalues:
                raise ValueError("pass labels positionally OR by name")
            labelvalues = tuple(
                labelkw[name] for name in self.labelnames
            )
        labelvalues = tuple(str(v) for v in labelvalues)
        if len(labelvalues) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}"
            )
        with self._lock:
            child = self._children.get(labelvalues)
            if child is None:
                child = _Child(self, labelvalues)
                self._children[labelvalues] = child
            return child

    def __getattr__(self, item):
        # Unlabeled metrics act as their own single child (counter.inc()).
        default = self.__dict__.get("_default")
        if default is not None and item in (
            "inc", "dec", "set", "observe", "quantile",
            "value", "count", "sum",
        ):
            return getattr(default, item)
        raise AttributeError(item)

    def expose(self, lines):
        lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.type}")
        if self._default is not None:
            self._default._expose(lines)
            return
        with self._lock:
            children = sorted(self._children.items())
        for _, child in children:
            child._expose(lines)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _get_or_create(self, name, help, type, labelnames, buckets=None):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is not None:
                if metric.type != type or metric.labelnames != tuple(
                    labelnames
                ):
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        f"type/labels"
                    )
                return metric
            metric = Metric(name, help, type, labelnames, buckets)
            self._metrics[name] = metric
            return metric

    def counter(self, name, help="", labelnames=()):
        return self._get_or_create(name, help, "counter", labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._get_or_create(name, help, "gauge", labelnames)

    def histogram(self, name, help="", labelnames=(), buckets=None):
        return self._get_or_create(
            name, help, "histogram", labelnames, buckets
        )

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def expose(self):
        """The full registry in Prometheus text-exposition format."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines = []
        for _, metric in metrics:
            metric.expose(lines)
        return "\n".join(lines) + "\n"


_default = MetricsRegistry()


def default_registry():
    """The process-global registry every component records into."""
    return _default
