"""elasticdl_tpu: a TPU-native elastic distributed training framework.

A ground-up JAX/XLA rebuild of the capabilities of ElasticDL (reference at
/root/reference): a master control plane that dynamically shards data into
tasks and elastically manages workers, a synchronous AllReduce data-parallel
path expressed as shard_map + XLA collectives over ICI/DCN, and a
parameter-server path with host-resident dense/sparse state and native C++
optimizer kernels.
"""

__version__ = "0.1.0"
