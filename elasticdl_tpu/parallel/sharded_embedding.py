"""Device-sharded embedding tables: rows across the mesh, lookup by
collectives — the TPU-first middle tier between "replicate the table" and
"host it on a parameter server".

The reference's only answer to a big table is the PS (EmbeddingDelegate
RPCs mid-forward, /root/reference/elasticdl/python/elasticdl/
embedding_delegate.py:74-106). On TPU, a table that exceeds one chip's HBM
but fits the SLICE's aggregate HBM should live sharded across the mesh and
be looked up with on-chip collectives riding ICI — the SparseCore-style
placement — keeping the PS for tables that don't fit the slice
(common/model_handler.py's threshold logic gains this as its upper tier).

Lookup pattern (inside shard_map, per device):
    1. all_gather the ids over the axis — every device sees the global
       id batch (ids are int32; this is the cheap collective),
    2. gather locally: each device answers the ids that fall in its row
       block, contributing zeros elsewhere,
    3. psum_scatter the stacked answers back — each requester receives
       exactly its batch shard's rows, summed over owners (one owner per
       id, the rest contributed zeros).
Autodiff reverses it for free: psum_scatter transposes to all_gather and
the masked gather transposes to a scatter-add into the local row block, so
the backward pass routes each row-gradient to the owning device with the
same two collectives.
"""

import jax
import jax.numpy as jnp
import numpy as np
from elasticdl_tpu.common.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

import flax.linen as nn


def padded_vocab(vocab, n_shards):
    """Rows are block-sharded; the table allocates vocab rounded up so
    every device owns an equal block (the pad rows are never addressed)."""
    return -(-vocab // n_shards) * n_shards


def sharded_embedding_lookup(table, ids, mesh, axis="data"):
    """Global [V, D] table (V divisible by the axis size) x [..., F] ids
    (leading dim sharded over `axis`) -> [..., F, D] rows with the ids'
    sharding. Call inside jit; the shard_map makes the collective pattern
    explicit instead of trusting the SPMD partitioner's gather handling."""
    n = mesh.shape[axis]

    def local(table_loc, ids_loc):
        # table_loc [V/n, D]; ids_loc [b, ...]: this device's batch shard.
        rows_per = table_loc.shape[0]
        rank = jax.lax.axis_index(axis)
        all_ids = jax.lax.all_gather(ids_loc, axis)  # [n, b, ...]
        rel = all_ids.astype(jnp.int32) - rank * rows_per
        mine = jnp.logical_and(rel >= 0, rel < rows_per)
        rows = jnp.take(
            table_loc, jnp.clip(rel, 0, rows_per - 1), axis=0
        )  # [n, b, ..., D]
        rows = jnp.where(mine[..., None], rows, 0.0)
        # [n, b, ..., D] -> [b, ..., D]: requester d gets sum over owners
        # of their answer block d (exactly one nonzero owner per id).
        # tiled psum_scatter keeps a leading block dim of n/n = 1.
        return jax.lax.psum_scatter(
            rows, axis, scatter_dimension=0, tiled=True
        )[0]

    in_rank = ids.ndim
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, *([None] * (in_rank - 1)))),
        out_specs=P(axis, *([None] * in_rank)),
        check_vma=False,
    )(table, ids)


class ShardedEmbed(nn.Module):
    """Drop-in nn.Embed analog whose table rows shard over a mesh axis.

    The param keeps the name ("embedding") and logical [vocab_padded, D]
    shape of a stock embed, so checkpoints transfer; pass
    `sharded_embed_specs` output through the trainer/jit in_shardings so
    the param is physically placed row-sharded."""

    num_embeddings: int
    features: int
    mesh: object  # jax.sharding.Mesh (static for the module tree)
    axis: str = "data"
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, ids):
        n = self.mesh.shape[self.axis]
        vocab = padded_vocab(self.num_embeddings, n)
        table = self.param(
            "embedding",
            nn.initializers.normal(stddev=0.01),
            (vocab, self.features),
            self.param_dtype,
        )
        return sharded_embedding_lookup(
            table, jnp.asarray(ids), self.mesh, self.axis
        )


def sharded_embed_spec(axis="data"):
    """PartitionSpec for a ShardedEmbed (or any row-sharded) table."""
    return P(axis, None)


def shard_table_rows(table, mesh, axis="data"):
    """Place a host/global [V, D] table row-sharded on the mesh (pads V up
    to the axis size first). Returns the global device array."""
    from jax.sharding import NamedSharding

    n = mesh.shape[axis]
    v = table.shape[0]
    vp = padded_vocab(v, n)
    if vp != v:
        table = np.concatenate(
            [np.asarray(table),
             np.zeros((vp - v, table.shape[1]), table.dtype)]
        )
    return jax.device_put(table, NamedSharding(mesh, P(axis, None)))
