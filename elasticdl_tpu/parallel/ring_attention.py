"""Ring attention: exact attention over sequences sharded across devices.

Capability extension beyond the reference (which is DP-only; SURVEY.md §5
marks long-context absent upstream). Each device holds one sequence block of
Q/K/V; K/V blocks rotate around the mesh's sequence axis with
`lax.ppermute` while every device folds each arriving block into a running
online-softmax accumulator (max, sum, acc) — the blockwise-parallel /
RingAttention scheme. Communication rides ICI; compute between hops is a
dense [S_local x S_local] attention block on the MXU, so the transfer of the
next block overlaps the math of the current one under XLA's async
collectives.

Causality across blocks uses the GLOBAL block order: device i skips blocks
j > i entirely (they're fully masked) and applies the triangular mask only
on its own diagonal block.
"""

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _block_attend(q, k, v, scale, mask=None):
    """One blockwise contribution: returns (m, l, acc) for q against this
    k/v block. q: [B,H,Sq,D]; k,v: [B,H,Sk,D]."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    m = jnp.max(scores, axis=-1)
    p = jnp.exp(scores - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m, l, acc


def _merge(m1, l1, acc1, m2, l2, acc2):
    """Merge two online-softmax partials (the associative combine)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return (
        m,
        l1 * a1 + l2 * a2,
        acc1 * a1[..., None] + acc2 * a2[..., None],
    )


def ring_attention(q, k, v, axis_name, causal=False):
    """Exact attention with Q/K/V sharded [B, H, S_local, D] along
    `axis_name`. Call INSIDE shard_map; returns the local output block.
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    scale = q.shape[-1] ** -0.5
    s_local = q.shape[2]

    m0 = jnp.full(q.shape[:-1], NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1], jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)

    # Ring: at step t this device holds the K/V block originally owned by
    # device (my_idx - t) mod N.
    def step(t, carry):
        m, l, acc, k_blk, v_blk = carry
        owner = (my_idx - t) % axis_size
        if causal:
            # Full block mask decisions by global block order.
            def masked_block():
                q_pos = my_idx * s_local + jax.lax.broadcasted_iota(
                    jnp.int32, (s_local, k_blk.shape[2]), 0
                )
                k_pos = owner * s_local + jax.lax.broadcasted_iota(
                    jnp.int32, (s_local, k_blk.shape[2]), 1
                )
                return _block_attend(
                    q, k_blk, v_blk, scale, mask=(q_pos >= k_pos)
                )

            def skip_block():
                return (
                    jnp.full(q.shape[:-1], NEG_INF, jnp.float32),
                    jnp.zeros(q.shape[:-1], jnp.float32),
                    jnp.zeros(q.shape, jnp.float32),
                )

            mb, lb, accb = jax.lax.cond(
                owner <= my_idx, masked_block, skip_block
            )
        else:
            mb, lb, accb = _block_attend(q, k_blk, v_blk, scale)
        m, l, acc = _merge(m, l, acc, mb, lb, accb)

        # Rotate K/V to the next device — except after the last fold,
        # where the rotated blocks would be discarded (saves one full
        # K/V ICI hop per attention call). All devices see the same t, so
        # the cond branches uniformly and the collective stays legal.
        def rotate(blocks):
            perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
            return tuple(
                jax.lax.ppermute(b, axis_name, perm) for b in blocks
            )

        k_next, v_next = jax.lax.cond(
            t + 1 < axis_size,
            rotate,
            lambda blocks: blocks,
            (k_blk, v_blk),
        )
        return m, l, acc, k_next, v_next

    m, l, acc, _, _ = jax.lax.fori_loop(
        0, axis_size, step, (m0, l0, acc0, k, v)
    )
    return (acc / l[..., None]).astype(q.dtype)


def make_ring_attention(mesh, axis_name="seq", causal=False,
                        batch_axis=None):
    """shard_map-wrapped ring attention: takes GLOBAL [B, H, S, D] arrays
    sharded on S (and optionally on B along `batch_axis` for DP+SP meshes)
    and returns the global output with the same sharding."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    spec = P(batch_axis, None, axis_name, None)
    return shard_map(
        functools.partial(
            ring_attention, axis_name=axis_name, causal=causal
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
