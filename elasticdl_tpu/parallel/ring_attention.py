"""Ring attention: exact attention over sequences sharded across devices.

Capability extension beyond the reference (which is DP-only; SURVEY.md §5
marks long-context absent upstream). Each device holds one sequence block of
Q/K/V; K/V blocks rotate around the mesh's sequence axis with
`lax.ppermute` while every device folds each arriving block into a running
online-softmax accumulator (max, sum, acc) — the blockwise-parallel /
RingAttention scheme. Communication rides ICI; compute between hops is a
dense [S_local x S_local] attention block on the MXU, so the transfer of the
next block overlaps the math of the current one under XLA's async
collectives.

Causality across blocks uses the GLOBAL block order: device i skips blocks
j > i entirely (they're fully masked) and applies the triangular mask only
on its own diagonal block.
"""

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def seq_axis_demand(context_parallel):
    """Sequence/context parallelism's mesh-axis contribution to world
    resolution (shared by ring attention and ulysses.py — both shard the
    same "seq" axis): intra-process, like model/stage, and the first
    axis the resolver drops when the trailing product stops dividing a
    world (the plain model trains identically without SP)."""
    from elasticdl_tpu.parallel.mesh import SEQ_AXIS, AxisDemand

    return AxisDemand(SEQ_AXIS, int(context_parallel), intra_process=True)


def _block_attend(q, k, v, scale, mask=None):
    """One blockwise contribution: returns (m, l, acc) for q against this
    k/v block. q: [B,H,Sq,D]; k,v: [B,H,Sk,D]."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    m = jnp.max(scores, axis=-1)
    p = jnp.exp(scores - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m, l, acc


def _merge(m1, l1, acc1, m2, l2, acc2):
    """Merge two online-softmax partials (the associative combine)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return (
        m,
        l1 * a1 + l2 * a2,
        acc1 * a1[..., None] + acc2 * a2[..., None],
    )


def _rotate_next(blocks, t, axis_name, axis_size):
    """Ring-shift K/V blocks to the next device for step t+1. Issued
    BEFORE the step's attention math (no data dependence on it), so XLA's
    async collectives stream the transfer over ICI while the MXU chews on
    the current block. Skipped after the last fold — the rotated blocks
    would be discarded, saving one full K/V hop per attention call. All
    devices see the same t, so the cond branches uniformly and the
    collective stays legal."""

    def rotate(bs):
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        return tuple(
            jax.lax.ppermute(b, axis_name, perm) for b in bs
        )

    return jax.lax.cond(
        t + 1 < axis_size, rotate, lambda bs: bs, tuple(blocks)
    )


def ring_attention(q, k, v, axis_name, causal=False):
    """Exact attention with Q/K/V sharded [B, H, S_local, D] along
    `axis_name`. Call INSIDE shard_map; returns the local output block.
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    scale = q.shape[-1] ** -0.5
    s_local = q.shape[2]

    m0 = jnp.full(q.shape[:-1], NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1], jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)

    # Ring: at step t this device holds the K/V block originally owned by
    # device (my_idx - t) mod N.
    def step(t, carry):
        m, l, acc, k_blk, v_blk = carry
        owner = (my_idx - t) % axis_size
        k_next, v_next = _rotate_next(
            (k_blk, v_blk), t, axis_name, axis_size
        )
        if causal:
            # Full block mask decisions by global block order.
            def masked_block():
                q_pos = my_idx * s_local + jax.lax.broadcasted_iota(
                    jnp.int32, (s_local, k_blk.shape[2]), 0
                )
                k_pos = owner * s_local + jax.lax.broadcasted_iota(
                    jnp.int32, (s_local, k_blk.shape[2]), 1
                )
                return _block_attend(
                    q, k_blk, v_blk, scale, mask=(q_pos >= k_pos)
                )

            def skip_block():
                return (
                    jnp.full(q.shape[:-1], NEG_INF, jnp.float32),
                    jnp.zeros(q.shape[:-1], jnp.float32),
                    jnp.zeros(q.shape, jnp.float32),
                )

            mb, lb, accb = jax.lax.cond(
                owner <= my_idx, masked_block, skip_block
            )
        else:
            mb, lb, accb = _block_attend(q, k_blk, v_blk, scale)
        m, l, acc = _merge(m, l, acc, mb, lb, accb)
        return m, l, acc, k_next, v_next

    m, l, acc, _, _ = jax.lax.fori_loop(
        0, axis_size, step, (m0, l0, acc0, k, v)
    )
    return (acc / l[..., None]).astype(q.dtype)


def make_ring_attention(mesh, axis_name="seq", causal=False,
                        batch_axis=None, head_axis=None):
    """shard_map-wrapped ring attention: takes GLOBAL [B, H, S, D] arrays
    sharded on S (and optionally on B along `batch_axis` for DP+SP meshes,
    and on H along `head_axis` for TP composition — heads are embarrassingly
    parallel in attention, so a head shard just runs its own ring) and
    returns the global output with the same sharding."""
    from jax.sharding import PartitionSpec as P
    from elasticdl_tpu.common.jax_compat import shard_map

    spec = P(batch_axis, head_axis, axis_name, None)
    return shard_map(
        functools.partial(
            ring_attention, axis_name=axis_name, causal=causal
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )


# ---------- zigzag variant: balanced causal ring ----------
#
# Plain causal ring attention is load-imbalanced: with contiguous sequence
# sharding, device 0 computes 1 block while device N-1 computes N (early
# ranks idle through skipped blocks). The zigzag assignment splits the
# sequence into 2N half-chunks and gives device r chunks (r, 2N-1-r) — one
# early + one late — so EVERY device computes exactly 2 half-blocks per ring
# step (3 on its diagonal step): per-step critical path ~halves and total
# work equalizes at 2N+1 half-blocks per device.


def _zigzag_perms(axis_size):
    """Static ppermutes moving half-chunks between contiguous and zigzag
    layouts. Contiguous: device d holds chunks (2d, 2d+1). Zigzag: chunk c
    lives on device c if c < N else 2N-1-c."""
    n = axis_size

    def owner(c):
        return c if c < n else 2 * n - 1 - c

    # First/second local halves, contiguous -> zigzag.
    fwd0 = [(d, owner(2 * d)) for d in range(n)]
    fwd1 = [(d, owner(2 * d + 1)) for d in range(n)]
    inv0 = [(dst, src) for src, dst in fwd0]
    inv1 = [(dst, src) for src, dst in fwd1]
    return fwd0, fwd1, inv0, inv1


def zigzag_ring_attention(q, k, v, axis_name, causal=True):
    """Balanced causal ring attention; call INSIDE shard_map with Q/K/V
    sharded [B, H, S_local, D] contiguously along `axis_name`. The zigzag
    relayout is internal: inputs/outputs stay contiguously sharded."""
    if not causal:
        return ring_attention(q, k, v, axis_name, causal=False)
    axis_size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    scale = q.shape[-1] ** -0.5
    s_local = q.shape[2]
    s_half = s_local // 2
    fwd0, fwd1, inv0, inv1 = _zigzag_perms(axis_size)

    def to_zigzag(x):
        # Send my first half (chunk 2my) and second half (chunk 2my+1) to
        # their zigzag owners; each device receives exactly one chunk per
        # permutation. Which received piece is the EARLY chunk (id my)
        # depends on my parity: chunk my arrived via perm (my % 2).
        a = jax.lax.ppermute(x[:, :, :s_half], axis_name, fwd0)
        b = jax.lax.ppermute(x[:, :, s_half:], axis_name, fwd1)
        even = (my % 2) == 0
        early = jnp.where(even, a, b)
        late = jnp.where(even, b, a)
        return early, late  # global chunks (my, 2N-1-my)

    def from_zigzag(early, late):
        # Inverse: chunk my returns via inv(my%2); chunk 2N-1-my via the
        # other (2N-1-my has opposite parity). Each device gets its chunk
        # 2d back through inv0 and 2d+1 through inv1.
        even = (my % 2) == 0
        via0 = jnp.where(even, early, late)
        via1 = jnp.where(even, late, early)
        first = jax.lax.ppermute(via0, axis_name, inv0)
        second = jax.lax.ppermute(via1, axis_name, inv1)
        return jnp.concatenate([first, second], axis=2)

    q_e, q_l = to_zigzag(q)
    k_e, k_l = to_zigzag(k)
    v_e, v_l = to_zigzag(v)

    shape_stats = q_e.shape[:-1]

    def empty():
        return (
            jnp.full(shape_stats, NEG_INF, jnp.float32),
            jnp.zeros(shape_stats, jnp.float32),
            jnp.zeros(q_e.shape, jnp.float32),
        )

    def diag_mask(sk):
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (s_half, sk), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (s_half, sk), 1)
        return q_pos >= k_pos

    def step(t, carry):
        me, le, ae, ml, ll, al, ke, kl, ve, vl = carry
        owner = (my - t) % axis_size
        ke_n, kl_n, ve_n, vl_n = _rotate_next(
            (ke, kl, ve, vl), t, axis_name, axis_size
        )

        # q early (chunk my) vs k early (chunk owner): full if owner < my,
        # diagonal if owner == my, skip if owner > my.
        def qe_ke():
            return jax.lax.cond(
                owner == my,
                lambda: _block_attend(
                    q_e, ke, ve, scale, mask=diag_mask(ke.shape[2])
                ),
                lambda: _block_attend(q_e, ke, ve, scale),
            )

        c1 = jax.lax.cond(owner <= my, qe_ke, empty)
        me, le, ae = _merge(me, le, ae, *c1)

        # q late (chunk 2N-1-my) vs k early (chunk owner < N): always full.
        c2 = _block_attend(q_l, ke, ve, scale)
        ml, ll, al = _merge(ml, ll, al, *c2)

        # q late vs k late (chunk 2N-1-owner): full if owner > my (earlier
        # chunk), diagonal if owner == my, skip if owner < my.
        def ql_kl():
            return jax.lax.cond(
                owner == my,
                lambda: _block_attend(
                    q_l, kl, vl, scale, mask=diag_mask(kl.shape[2])
                ),
                lambda: _block_attend(q_l, kl, vl, scale),
            )

        c3 = jax.lax.cond(owner >= my, ql_kl, empty)
        ml, ll, al = _merge(ml, ll, al, *c3)
        # (q early vs k late is always in the future: never computed.)
        return me, le, ae, ml, ll, al, ke_n, kl_n, ve_n, vl_n

    m0e, l0e, a0e = empty()
    m0l, l0l, a0l = empty()
    me, le, ae, ml, ll, al, *_ = jax.lax.fori_loop(
        0, axis_size, step,
        (m0e, l0e, a0e, m0l, l0l, a0l, k_e, k_l, v_e, v_l),
    )
    out_e = (ae / le[..., None]).astype(q.dtype)
    out_l = (al / ll[..., None]).astype(q.dtype)
    return from_zigzag(out_e, out_l)


def make_zigzag_ring_attention(mesh, axis_name="seq", causal=True,
                               batch_axis=None, head_axis=None):
    """shard_map-wrapped zigzag ring attention (balanced causal SP). Same
    contract as make_ring_attention; requires an even per-device sequence."""
    from jax.sharding import PartitionSpec as P
    from elasticdl_tpu.common.jax_compat import shard_map

    spec = P(batch_axis, head_axis, axis_name, None)
    return shard_map(
        functools.partial(
            zigzag_ring_attention, axis_name=axis_name, causal=causal
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
