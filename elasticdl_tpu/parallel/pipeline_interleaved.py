"""Interleaved 1F1B pipeline parallelism (virtual pipeline stages).

Megatron-style interleaving on top of the 1F1B machinery in pipeline.py:
the Block stack splits into n_stages * v CHUNKS, device d hosting chunks
{d, d+N, ...} so every chunk-to-chunk hop is still a neighbor-only
ppermute (forward to d+1, gradient to d-1). The execution order comes
from a STATIC schedule table (parallel/pipeline_schedule.py) consumed as
scan data: per tick each device runs one table-assigned fwd slot and one
bwd slot (masked when idle), messages carry a slot tag and land in small
exactly-sized mailboxes, per-chunk inputs stash in a ring for the
vjp-recompute backward, and the LM head stays vocab-parallel across the
stage axis exactly as in make_lm_pipeline_1f1b.

Same public contract as make_lm_pipeline_1f1b — (init_fn,
loss_and_grads_fn) over the {"embed", "stages", "head"} tree with
"stages" stacked in GLOBAL CHUNK ORDER [n*v, ...] (checkpoint-compatible
with a GPipe/1F1B build of n*v stages); rows are permuted into the
device-block layout internally and gradients permuted back.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from elasticdl_tpu.common.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.models.transformer.transformer_lm import (
    Block,
    embed_input,
)
from elasticdl_tpu.parallel.pipeline import (
    make_lm_pipeline,
    microbatch,
    vocab_parallel_head_loss,
)
from elasticdl_tpu.parallel.pipeline_schedule import (
    build_interleaved_schedule,
)


def interleaved_row_order(n_stages, v):
    """Permutation taking chunk-ordered rows [c] to device-block order:
    position d*v + r holds chunk r*n_stages + d (device d's r-th local
    chunk)."""
    order = []
    for d in range(n_stages):
        for r in range(v):
            order.append(r * n_stages + d)
    return np.asarray(order, np.int32)


def make_lm_pipeline_interleaved(cfg, mesh, n_stages, v, num_microbatches,
                                 axis_name="stage", batch_axis=None):
    total = n_stages * v
    if cfg.n_layers % total:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by "
            f"{n_stages} stages x {v} chunks"
        )
    if cfg.vocab % n_stages:
        raise ValueError(
            f"vocab {cfg.vocab} not divisible by {n_stages} stages "
            f"(the head is vocab-parallel over the stage axis)"
        )
    layers_per_chunk = cfg.n_layers // total
    v_loc = cfg.vocab // n_stages
    act_dtype = jnp.dtype(cfg.activation_dtype)
    sched = build_interleaved_schedule(n_stages, v, num_microbatches)
    order = interleaved_row_order(n_stages, v)
    inverse = np.argsort(order)

    class EmbedIn(nn.Module):
        @nn.compact
        def __call__(self, tokens):
            return embed_input(cfg, tokens)

    class Chunk(nn.Module):
        @nn.compact
        def __call__(self, x, training=False):
            for _ in range(layers_per_chunk):
                x = Block(cfg)(x, training)
            return x

    embed_mod, chunk_mod = EmbedIn(), Chunk()
    head_ln = nn.LayerNorm(dtype=act_dtype)

    def init_fn(rng, sample_tokens):
        # Same tree as a GPipe/1F1B build with n*v stages (chunk order).
        gpipe_init, _ = make_lm_pipeline(
            cfg, mesh, total, num_microbatches,
            axis_name=axis_name, batch_axis=batch_axis,
        )
        return gpipe_init(rng, sample_tokens)

    def _head_loss(head_params, y, labels_m, shard):
        return vocab_parallel_head_loss(
            cfg, head_ln, v_loc, axis_name, head_params, y, labels_m,
            shard,
        )

    def _chunk_forward(chunk_params, embed_params, x_in, tokens_m,
                       is_first, rng_m):
        """Uniform slot program: chunk 0 embeds its tokens, everything
        else consumes the mailbox activation; jnp.where routes the
        gradients (the unselected branch gets a zero cotangent)."""
        emb = embed_mod.apply({"params": embed_params}, tokens_m)
        h = jnp.where(is_first, emb, x_in)
        if rng_m is None:
            return chunk_mod.apply({"params": chunk_params}, h, True)
        return chunk_mod.apply(
            {"params": chunk_params}, h, True, rngs={"dropout": rng_m}
        )

    def _pipeline(stages_dev, embed_p, head_p, tokens_mb, labels_mb,
                  tables, rng):
        n = n_stages
        shard = jax.lax.axis_index(axis_name)
        # stages_dev: local [v, ...] rows = this device's chunks r*n+d.
        chunks_local = stages_dev
        mb, s = tokens_mb.shape[1], tokens_mb.shape[2]
        act_shape = (mb, s, cfg.d_model)
        m_total = num_microbatches
        perm_fwd = [(i, (i + 1) % n) for i in range(n)]
        perm_bwd = [(i, (i - 1) % n) for i in range(n)]

        def rng_for(c, m):
            if rng is None:
                return None
            r = jax.random.fold_in(jax.random.fold_in(rng, c), m)
            if batch_axis is not None:
                r = jax.random.fold_in(
                    r, jax.lax.axis_index(batch_axis)
                )
            return r

        def chunk_params_at(r):
            return jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, r, 0, keepdims=False
                ),
                chunks_local,
            )

        zero_chunk_grads = jax.tree_util.tree_map(
            jnp.zeros_like, chunks_local
        )

        def tick(carry, xs):
            (fwd_box, bwd_box, stash, dy_box, grads, loss_sum) = carry
            d_stages, d_embed, d_head = grads
            fc, fm, bc, bm, head_m = xs
            # Tables are per-device columns already (sharded over the
            # stage axis); squeeze the length-1 device dim.
            fc, fm = fc[0], fm[0]
            bc, bm = bc[0], bm[0]
            head_m = head_m[0]

            # ---------- fwd slot ----------
            f_active = fc >= 0
            fc_s = jnp.maximum(fc, 0)
            fm_s = jnp.clip(fm, 0, m_total - 1)
            r_f = fc_s // n
            tokens_f = jax.lax.dynamic_index_in_dim(
                tokens_mb, fm_s, 0, keepdims=False
            )
            in_tag = (fc_s * m_total + fm_s) % sched.fwd_mailbox
            x_in = jax.lax.dynamic_index_in_dim(
                fwd_box, in_tag, 0, keepdims=False
            )
            y = _chunk_forward(
                chunk_params_at(r_f), embed_p, x_in, tokens_f,
                fc_s == 0, rng_for(fc_s, fm_s),
            )
            # Stash the consumed input for this slot's backward.
            st_slot = r_f * sched.stash_depth + fm_s % sched.stash_depth
            cur = jax.lax.dynamic_index_in_dim(
                stash, st_slot, 0, keepdims=False
            )
            stash = jax.lax.dynamic_update_index_in_dim(
                stash, jnp.where(f_active, x_in, cur), st_slot, 0
            )

            # ---------- head (vocab-parallel, all devices) ----------
            h_active = head_m >= 0
            hm_s = jnp.clip(head_m, 0, m_total - 1)
            y_last = jax.lax.psum(
                jnp.where(
                    jnp.logical_and(f_active, fc == total - 1), y, 0.0
                ),
                axis_name,
            )
            labels_h = jax.lax.dynamic_index_in_dim(
                labels_mb, hm_s, 0, keepdims=False
            )
            loss_m, head_vjp = jax.vjp(
                lambda hp, yy: _head_loss(hp, yy, labels_h, shard),
                head_p,
                y_last,
            )
            d_head_c, dy = head_vjp(jnp.float32(1.0 / m_total))
            dy = jax.lax.psum(dy, axis_name) / n  # psum-transpose factor
            loss_sum = loss_sum + jnp.where(
                h_active, loss_m / m_total, 0.0
            )
            d_head = jax.tree_util.tree_map(
                lambda acc, g: acc + jnp.where(h_active, g, 0.0),
                d_head,
                d_head_c,
            )
            dy_slot = hm_s % sched.dy_store
            cur_dy = jax.lax.dynamic_index_in_dim(
                dy_box, dy_slot, 0, keepdims=False
            )
            dy_box = jax.lax.dynamic_update_index_in_dim(
                dy_box,
                jnp.where(h_active, dy.astype(act_dtype), cur_dy),
                dy_slot,
                0,
            )

            # ---------- bwd slot ----------
            b_active = bc >= 0
            bc_s = jnp.maximum(bc, 0)
            bm_s = jnp.clip(bm, 0, m_total - 1)
            r_b = bc_s // n
            g_tag = (bc_s * m_total + bm_s) % sched.bwd_mailbox
            g_box = jax.lax.dynamic_index_in_dim(
                bwd_box, g_tag, 0, keepdims=False
            )
            g_dy = jax.lax.dynamic_index_in_dim(
                dy_box, bm_s % sched.dy_store, 0, keepdims=False
            )
            g = jnp.where(bc == total - 1, g_dy, g_box)
            x_b = jax.lax.dynamic_index_in_dim(
                stash,
                r_b * sched.stash_depth + bm_s % sched.stash_depth,
                0,
                keepdims=False,
            )
            tokens_b = jax.lax.dynamic_index_in_dim(
                tokens_mb, bm_s, 0, keepdims=False
            )
            _, chunk_vjp = jax.vjp(
                lambda cp, ep, xx: _chunk_forward(
                    cp, ep, xx, tokens_b, bc_s == 0,
                    rng_for(bc_s, bm_s),
                ),
                chunk_params_at(r_b),
                embed_p,
                x_b,
            )
            d_chunk, d_embed_c, dx = chunk_vjp(g)
            d_stages = jax.tree_util.tree_map(
                lambda acc, gg: acc.at[r_b].add(
                    jnp.where(b_active, gg, 0.0)
                ),
                d_stages,
                d_chunk,
            )
            d_embed = jax.tree_util.tree_map(
                lambda acc, gg: acc + jnp.where(b_active, gg, 0.0),
                d_embed,
                d_embed_c,
            )

            # ---------- neighbor hops (message + tag) ----------
            send_f = jnp.logical_and(f_active, fc < total - 1)
            f_msg = jax.lax.ppermute(
                jnp.where(send_f, y, 0.0), axis_name, perm_fwd
            )
            f_tag = jax.lax.ppermute(
                jnp.where(send_f, (fc_s + 1) * m_total + fm_s, -1),
                axis_name,
                perm_fwd,
            )
            send_b = jnp.logical_and(b_active, bc > 0)
            b_msg = jax.lax.ppermute(
                jnp.where(send_b, dx, 0.0), axis_name, perm_bwd
            )
            b_tag = jax.lax.ppermute(
                jnp.where(send_b, (bc_s - 1) * m_total + bm_s, -1),
                axis_name,
                perm_bwd,
            )
            f_slot = jnp.maximum(f_tag, 0) % sched.fwd_mailbox
            cur_f = jax.lax.dynamic_index_in_dim(
                fwd_box, f_slot, 0, keepdims=False
            )
            fwd_box = jax.lax.dynamic_update_index_in_dim(
                fwd_box, jnp.where(f_tag >= 0, f_msg, cur_f), f_slot, 0
            )
            b_slot = jnp.maximum(b_tag, 0) % sched.bwd_mailbox
            cur_b = jax.lax.dynamic_index_in_dim(
                bwd_box, b_slot, 0, keepdims=False
            )
            bwd_box = jax.lax.dynamic_update_index_in_dim(
                bwd_box, jnp.where(b_tag >= 0, b_msg, cur_b), b_slot, 0
            )
            return (
                fwd_box,
                bwd_box,
                stash,
                dy_box,
                (d_stages, d_embed, d_head),
                loss_sum,
            ), None

        carry0 = (
            jnp.zeros((sched.fwd_mailbox, *act_shape), act_dtype),
            jnp.zeros((sched.bwd_mailbox, *act_shape), act_dtype),
            jnp.zeros((v * sched.stash_depth, *act_shape), act_dtype),
            jnp.zeros((sched.dy_store, *act_shape), act_dtype),
            (
                zero_chunk_grads,
                jax.tree_util.tree_map(jnp.zeros_like, embed_p),
                jax.tree_util.tree_map(jnp.zeros_like, head_p),
            ),
            jnp.float32(0.0),
        )
        (_, _, _, _, grads, loss_sum), _ = jax.lax.scan(
            tick, carry0, tables
        )
        d_stages, d_embed, d_head = grads
        d_embed = jax.tree_util.tree_map(
            lambda gg: jax.lax.psum(gg, axis_name), d_embed
        )
        d_head = jax.tree_util.tree_map(
            lambda gg: jax.lax.psum(gg, axis_name) / n, d_head
        )
        loss = jax.lax.pmean(loss_sum, axis_name)
        if batch_axis is not None:
            d_embed, d_head, d_stages, loss = jax.tree_util.tree_map(
                lambda gg: jax.lax.pmean(gg, batch_axis),
                (d_embed, d_head, d_stages, loss),
            )
        return loss, {
            "embed": d_embed,
            "stages": d_stages,
            "head": d_head,
        }

    def loss_and_grads_fn(params, tokens, labels, rng=None):
        if bool(cfg.dropout) and rng is None:
            raise ValueError(
                "training with cfg.dropout > 0 requires an explicit rng"
            )
        tokens_mb = microbatch(
            jnp.asarray(tokens, jnp.int32), num_microbatches
        )
        labels_mb = microbatch(
            jnp.asarray(labels, jnp.int32), num_microbatches
        )
        # Chunk-ordered stages -> device-block layout for P(axis) on
        # dim 0 (device d's contiguous block = its local chunks).
        stages_dev = jax.tree_util.tree_map(
            lambda a: jnp.take(a, order, axis=0), params["stages"]
        )
        # Schedule tables ride the scan as xs; per-device columns shard
        # over the stage axis so each device reads only its own slots.
        tables = (
            jnp.asarray(sched.fwd_chunk),
            jnp.asarray(sched.fwd_micro),
            jnp.asarray(sched.bwd_chunk),
            jnp.asarray(sched.bwd_micro),
            jnp.asarray(sched.head_micro)[:, None].repeat(
                n_stages, axis=1
            ),
        )
        stage_specs = jax.tree_util.tree_map(
            lambda _: P(axis_name), params["stages"]
        )
        repl_e = jax.tree_util.tree_map(lambda _: P(), params["embed"])
        repl_h = jax.tree_util.tree_map(lambda _: P(), params["head"])
        x_spec = P(None, batch_axis)
        table_spec = P(None, axis_name)
        in_specs = (
            stage_specs, repl_e, repl_h, x_spec, x_spec,
            (table_spec,) * 5,
        )
        out_specs = (
            P(),
            {"embed": repl_e, "stages": stage_specs, "head": repl_h},
        )
        if rng is None:
            runner = shard_map(
                lambda sp, ep, hp, tm, lm, tb: _pipeline(
                    sp, ep, hp, tm, lm, tb, None
                ),
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=False,
            )
            loss, grads = runner(
                stages_dev, params["embed"], params["head"],
                tokens_mb, labels_mb, tables,
            )
        else:
            runner = shard_map(
                _pipeline,
                mesh=mesh,
                in_specs=in_specs + (P(),),
                out_specs=out_specs,
                check_vma=False,
            )
            loss, grads = runner(
                stages_dev, params["embed"], params["head"],
                tokens_mb, labels_mb, tables, rng,
            )
        # Device-block grads -> chunk order (the public tree layout).
        grads["stages"] = jax.tree_util.tree_map(
            lambda a: jnp.take(a, inverse, axis=0), grads["stages"]
        )
        return loss, grads

    return init_fn, loss_and_grads_fn
