"""Rank-0 state broadcast for elastic AllReduce regroups.

Replaces Horovod's `broadcast_variables(root_rank=0)` after a re-rendezvous
(/root/reference/elasticdl/python/worker/allreduce_trainer.py:150-152): the
rank-0 worker serves its (variables, opt_state, version) over gRPC; joining
or regrouping workers pull and overwrite their local state. Pytrees cross
the wire as position-indexed leaves — every worker runs the same model code,
so treedefs agree and the receiver unflattens with its own local treedef.
"""


import jax
import numpy as np

from elasticdl_tpu.common import rpc, tensor_utils
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

logger = get_logger("parallel.broadcast")


def state_to_model_pb(variables, opt_state, version):
    model = pb.Model(version=version)
    for prefix, tree in (("v", variables), ("o", opt_state)):
        for i, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
            model.dense_parameters.append(
                tensor_utils.ndarray_to_tensor_pb(
                    np.asarray(leaf), f"{prefix}{i}"
                )
            )
    return model


def model_pb_to_state(model, variables_treedef, opt_treedef):
    v_leaves, o_leaves = {}, {}
    for t in model.dense_parameters:
        arr = tensor_utils.tensor_pb_to_ndarray(t)
        (v_leaves if t.name[0] == "v" else o_leaves)[int(t.name[1:])] = arr
    variables = jax.tree_util.tree_unflatten(
        variables_treedef, [v_leaves[i] for i in range(len(v_leaves))]
    )
    opt_state = jax.tree_util.tree_unflatten(
        opt_treedef, [o_leaves[i] for i in range(len(o_leaves))]
    )
    return variables, opt_state, model.version


class BroadcastServicer:
    """Serves the owning trainer's current state. `provider` returns
    (variables, opt_state, version) or None while uninitialized."""

    def __init__(self, provider):
        self._provider = provider

    def pull_model(self, request, context):
        state = self._provider()
        if state is None:
            return pb.Model(version=-1)
        return state_to_model_pb(*state)


class BroadcastServer:
    def __init__(self, provider, port=0):
        self._server, self.port = rpc.serve(
            BroadcastServicer(provider), rpc.COLLECTIVE_SERVICE, port=port
        )
        logger.info("Broadcast server on port %d", self.port)

    def stop(self):
        self._server.stop(0)


def pull_state(coordinator_addr, variables_treedef, opt_treedef, timeout=30):
    """Pull rank-0 state. Returns (variables, opt_state, version) or None if
    the coordinator has no state yet."""
    import time as _time

    # The readiness probe and the RPC share ONE `timeout` budget: a
    # regrouping worker may dial the coordinator while it is itself still
    # re-binding after an elastic event, and a probe that ate the whole
    # budget must not buy the RPC a second one (that would double rejoin
    # latency exactly in the elastic path).
    start = _time.time()
    channel = rpc.build_channel(coordinator_addr, ready_timeout=timeout)
    try:
        stub = rpc.Stub(channel, rpc.COLLECTIVE_SERVICE)
        remaining = max(1.0, timeout - (_time.time() - start))
        model = stub.pull_model(
            pb.PullDenseParametersRequest(), timeout=remaining
        )
        if model.version < 0:
            return None
        return model_pb_to_state(model, variables_treedef, opt_treedef)
    finally:
        channel.close()
