"""jax.distributed lifecycle for elastic multi-host worlds.

The reference re-initializes Horovod whenever the master bumps the
rendezvous id (/root/reference/elasticdl/python/worker/
allreduce_trainer.py:46-75: hvd.shutdown() + hvd.init()). The TPU analog:
tear down and re-create the JAX coordination service connection with the new
(coordinator, world_size, rank) triple, after which jax.devices() shows the
new global device set and freshly-built meshes span the new world.

Single-process deployments (tests, LOCAL strategy, one TPU host) never call
initialize — the local platform is the world.
"""

import jax

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("parallel.distributed")


def _clear_backends():
    try:
        from jax.extend.backend import clear_backends

        clear_backends()
    except Exception:
        logger.warning("could not clear XLA backends", exc_info=True)

_current = {
    "coordinator": None,
    "world": 0,
    "rank": -1,
    "epoch": -1,
    "live": False,
}

# Elasticity-tuned timeouts. The shutdown barrier is best-effort: after a
# peer SIGKILL the survivors' barrier can never complete, so it must fail
# fast (and be swallowed) rather than hold up the re-mesh for the default
# 300 s. Heartbeat stays above the gloo collective timeout (~30 s) so an
# in-flight collective surfaces a catchable step error before the
# coordination client's process-killing health check fires.
INITIALIZATION_TIMEOUT_SECONDS = 120
SHUTDOWN_TIMEOUT_SECONDS = 10
HEARTBEAT_TIMEOUT_SECONDS = 60


def _shutdown_quietly():
    try:
        jax.distributed.shutdown()
    except Exception:
        # Failed shutdown barrier (dead peer) or an already-errored
        # coordination client: the world is being abandoned either way.
        logger.warning(
            "Distributed shutdown was not clean (peer death is the usual "
            "cause); proceeding with teardown",
            exc_info=True,
        )


def ensure_world(coordinator_addr, world_size, rank, epoch=None):
    """(Re)join the distributed world described by the triple. No-ops only
    when already a member of this world AT THIS membership epoch — the epoch
    matters because a survivor's (coordinator, world, rank) can be unchanged
    across a swap (B dies, C joins) while the coordination service still
    needs a full re-init for the newcomer to rendezvous. world_size == 1
    tears down any previous multi-host state and runs single-process."""
    same = (
        _current["live"]
        and _current["coordinator"] == coordinator_addr
        and _current["world"] == world_size
        and _current["rank"] == rank
        and epoch is not None
        and _current["epoch"] == epoch
    )
    if same:
        return
    if _current["live"]:
        logger.info("Leaving distributed world %s", _current)
        _shutdown_quietly()
        _current["live"] = False
        # Drop the cached backends so the old world's device topology
        # can't leak into world_size<=1 callers; the join path below also
        # clears unconditionally before re-initializing. Compiled
        # functions from the old world are invalid either way; trainers
        # rebuild their jitted steps after a regroup.
        _clear_backends()
    if world_size <= 1:
        _current.update(coordinator=None, world=1, rank=0, epoch=epoch)
        return
    logger.info(
        "Joining world coordinator=%s size=%d rank=%d epoch=%s",
        coordinator_addr,
        world_size,
        rank,
        epoch,
    )
    try:
        # Cross-process CPU collectives need the gloo implementation; a
        # no-op on TPU deployments.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        logger.warning(
            "could not select gloo CPU collectives; cross-process CPU "
            "worlds may fail",
            exc_info=True,
        )
    # jax.distributed.initialize refuses to run once a backend is
    # initialized — true both on a FIRST join from a process that already
    # ran JAX computations (a trainer that built params before discovering
    # its world) and on a rejoin. Drop any cached backends; callers must
    # host-snapshot device state BEFORE calling (the trainer does).
    _clear_backends()
    init_kwargs = dict(
        coordinator_address=coordinator_addr,
        num_processes=world_size,
        process_id=rank,
        initialization_timeout=INITIALIZATION_TIMEOUT_SECONDS,
        shutdown_timeout_seconds=SHUTDOWN_TIMEOUT_SECONDS,
        heartbeat_timeout_seconds=HEARTBEAT_TIMEOUT_SECONDS,
    )
    # Older jax (< 0.5) has neither timeout knob; drop what the installed
    # signature doesn't accept rather than crash every multi-host worker.
    import inspect

    accepted = inspect.signature(
        jax.distributed.initialize
    ).parameters
    init_kwargs = {
        k: v for k, v in init_kwargs.items() if k in accepted
    }
    jax.distributed.initialize(**init_kwargs)
    _current.update(
        coordinator=coordinator_addr,
        world=world_size,
        rank=rank,
        epoch=epoch,
        live=True,
    )


def is_live():
    """True while this process is a member of a live multi-host world —
    i.e. a world change would re-initialize jax.distributed and tear
    down every compiled executable (the regroup fast path keys on the
    negation)."""
    return _current["live"]


def leave_world():
    if _current["live"]:
        _shutdown_quietly()
        _current["live"] = False
