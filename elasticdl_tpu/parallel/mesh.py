"""Device mesh construction and batch sharding helpers.

The reference's allreduce path gets its topology from Horovod's Gloo ring
(/root/reference/elasticdl/python/worker/allreduce_trainer.py:77-83). The
TPU-native equivalent is a named `jax.sharding.Mesh`: data parallelism is the
"data" axis, tensor/model parallelism "model", sequence/context parallelism
"seq". XLA lowers psum/all_gather over the mesh to ICI collectives on real
hardware; nothing here is CPU/TPU specific.
"""

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
# Pipeline-parallel stage axis (parallel/pipeline.py): stacked per-stage
# params shard their leading dim over it. Like MODEL_AXIS it never crosses
# process boundaries (the multi-host composition invariant documented in
# worker/allreduce_trainer.py).
STAGE_AXIS = "stage"
# Intra-process slice of the data dimension, used by multi-host ZeRO-1:
# optimizer state shards over it while staying replicated across processes,
# so every process keeps a fully-addressable copy (elastic regroups can
# snapshot/broadcast it without the dead world's participation).
ZERO_AXIS = "zero"


def process_grouped_devices():
    """All global devices ordered so each process's devices are contiguous
    (sorted by (process_index, id)). A flat reshape over this list keeps
    any trailing mesh axis whose size divides local_device_count entirely
    inside one process — the invariant multi-host TP/ZeRO-1 rely on for
    fully-addressable parameters."""
    return sorted(jax.devices(), key=lambda d: (d.process_index, d.id))


def batch_axes(mesh: Mesh):
    """The mesh axes a batch's leading dim shards over: the data axis plus
    the intra-process zero axis when present (a {data, zero} mesh is pure
    data parallelism expressed as two factors)."""
    axes = [a for a in (DATA_AXIS, ZERO_AXIS) if a in mesh.shape]
    return tuple(axes)


def data_parallel_size(mesh: Mesh):
    import math as _math

    return _math.prod(mesh.shape[a] for a in batch_axes(mesh))


def make_mesh(axis_sizes=None, devices=None) -> Mesh:
    """Build a Mesh over `devices` (default: all visible, which under
    jax.distributed is the *global* device set across hosts).

    axis_sizes: ordered {axis_name: size} dict; a single size of -1 (or a
    missing remainder) absorbs all remaining devices. Default: 1-D data mesh.
    """
    explicit_devices = devices is not None
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    if axis_sizes is None:
        axis_sizes = {DATA_AXIS: len(devices)}
    names = tuple(axis_sizes)
    sizes = list(axis_sizes.values())
    n_fill = sizes.count(-1)
    if n_fill > 1:
        raise ValueError("at most one axis may have size -1")
    if n_fill == 1:
        known = math.prod(s for s in sizes if s != -1)
        if len(devices) % known:
            raise ValueError(
                f"{len(devices)} devices not divisible by fixed axes {known}"
            )
        sizes[sizes.index(-1)] = len(devices) // known
    total = math.prod(sizes)
    if total > len(devices):
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} wants {total} devices, "
            f"only {len(devices)} visible"
        )
    chosen = devices[:total]
    if not explicit_devices and total == len(devices):
        # Let mesh_utils lay the logical axes onto the physical ICI
        # topology (torus-neighbor rings per axis) instead of a flat
        # device-id reshape — on real multi-chip slices this is the
        # difference between collectives riding nearest-neighbor ICI
        # links and hopping across the torus. Only when the caller did
        # not pass an explicit device list (mesh_utils reorders, which
        # would silently discard a deliberate ordering); falls back to
        # the plain reshape off-TPU or for partial meshes.
        try:
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_device_mesh(
                tuple(sizes), devices=list(chosen)
            )
            return Mesh(arr, axis_names=names)
        except (
            ImportError,
            ValueError,
            NotImplementedError,
            # mesh_utils' TPU topology code bounds-checks with bare
            # asserts and raises RuntimeError on exotic slice shapes;
            # the flat reshape below is always a working layout.
            AssertionError,
            RuntimeError,
        ) as e:
            from elasticdl_tpu.common.log_utils import get_logger

            get_logger("parallel.mesh").warning(
                "Physical-topology mesh layout unavailable (%s); using "
                "flat device-id reshape — multi-chip collectives may "
                "cross non-neighbor ICI links", e,
            )
    return Mesh(chosen.reshape(sizes), axis_names=names)


def data_sharding(mesh: Mesh, axis=None) -> NamedSharding:
    """Leading-dim batch sharding over the data axis (plus the zero axis
    when the mesh factors data parallelism into two axes). Pass an explicit
    axis name or tuple to override."""
    if axis is None:
        axis = batch_axes(mesh) or DATA_AXIS
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_batch_to_multiple(batch, multiple):
    """Pad a numpy batch pytree's leading dim up to a multiple by cyclic
    repetition. Returns (padded_batch, real_n). The training step slices
    outputs back to real_n before the loss so padding rows never contribute
    gradient signal.
    """
    leaves = jax.tree_util.tree_leaves(batch)
    if not leaves:
        return batch, 0
    real_n = leaves[0].shape[0]
    padded_n = -(-real_n // multiple) * multiple
    if padded_n == real_n:
        return batch, real_n
    idx = np.arange(padded_n) % real_n
    padded = jax.tree_util.tree_map(
        lambda x: np.take(x, idx, axis=0), batch
    )
    return padded, real_n


def shard_batch(batch, mesh: Mesh, axis=None):
    """Place a host batch onto the mesh, sharded along the data axis.

    Single-host: plain device_put. Multi-host (jax.process_count() > 1): each
    process holds its local slice of the global batch and contributes it via
    make_array_from_process_local_data — the global array's leading dim is
    world_batch = local_batch * num_processes.
    """
    sharding = data_sharding(mesh, axis)
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(sharding, x),
            batch,
        )
    return jax.device_put(batch, sharding)
