"""Device mesh construction, batch sharding helpers, and the WORLD SPEC —
the single deterministic map from (parallel config, world topology) to the
mesh an elastic trainer builds.

The reference's allreduce path gets its topology from Horovod's Gloo ring
(/root/reference/elasticdl/python/worker/allreduce_trainer.py:77-83). The
TPU-native equivalent is a named `jax.sharding.Mesh`: data parallelism is the
"data" axis, tensor/model parallelism "model", sequence/context parallelism
"seq". XLA lowers psum/all_gather over the mesh to ICI collectives on real
hardware; nothing here is CPU/TPU specific.

World spec (`resolve_world_spec`): every parallel feature — ZeRO-1
(parallel/zero1.py), tensor parallelism (tensor_parallel.py), pipelining
(pipeline*.py), sequence parallelism (ring_attention.py / ulysses.py) —
contributes an `AxisDemand` naming the mesh axis it needs; the resolver
composes them under one precedence policy (stage excludes model/seq; seq
drops before model; zero only factors pure DP) into a `WorldSpec`. The
spec is a pure function of `(ParallelConfig, WorldTopology)`: given the
same config, an N-device world always maps to the same axes — which is
what lets a trainer compile the step of a world it is NOT in yet
(speculative AOT, worker/world_speculator.py) and recognize a membership
epoch bump that does not change the mesh at all (the recompile-free
regroup fast path). Mesh construction anywhere else in the tree is
rejected by the `mesh-spec-consistency` lint rule: the spec API here is
the only place a Mesh may be born.
"""

import math
from typing import Callable, NamedTuple, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
# Pipeline-parallel stage axis (parallel/pipeline.py): stacked per-stage
# params shard their leading dim over it. Like MODEL_AXIS it never crosses
# process boundaries (the multi-host composition invariant documented in
# worker/allreduce_trainer.py).
STAGE_AXIS = "stage"
# Intra-process slice of the data dimension, used by multi-host ZeRO-1:
# optimizer state shards over it while staying replicated across processes,
# so every process keeps a fully-addressable copy (elastic regroups can
# snapshot/broadcast it without the dead world's participation).
ZERO_AXIS = "zero"


def process_grouped_devices():
    """All global devices ordered so each process's devices are contiguous
    (sorted by (process_index, id)). A flat reshape over this list keeps
    any trailing mesh axis whose size divides local_device_count entirely
    inside one process — the invariant multi-host TP/ZeRO-1 rely on for
    fully-addressable parameters."""
    return sorted(jax.devices(), key=lambda d: (d.process_index, d.id))


def batch_axes(mesh: Mesh):
    """The mesh axes a batch's leading dim shards over: the data axis plus
    the intra-process zero axis when present (a {data, zero} mesh is pure
    data parallelism expressed as two factors)."""
    axes = [a for a in (DATA_AXIS, ZERO_AXIS) if a in mesh.shape]
    return tuple(axes)


def data_parallel_size(mesh: Mesh):
    import math as _math

    return _math.prod(mesh.shape[a] for a in batch_axes(mesh))


def make_mesh(axis_sizes=None, devices=None) -> Mesh:
    """Build a Mesh over `devices` (default: all visible, which under
    jax.distributed is the *global* device set across hosts).

    axis_sizes: ordered {axis_name: size} dict; a single size of -1 (or a
    missing remainder) absorbs all remaining devices. Default: 1-D data mesh.
    """
    explicit_devices = devices is not None
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    if axis_sizes is None:
        axis_sizes = {DATA_AXIS: len(devices)}
    names = tuple(axis_sizes)
    sizes = list(axis_sizes.values())
    n_fill = sizes.count(-1)
    if n_fill > 1:
        raise ValueError("at most one axis may have size -1")
    if n_fill == 1:
        known = math.prod(s for s in sizes if s != -1)
        if len(devices) % known:
            raise ValueError(
                f"{len(devices)} devices not divisible by fixed axes {known}"
            )
        sizes[sizes.index(-1)] = len(devices) // known
    total = math.prod(sizes)
    if total > len(devices):
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} wants {total} devices, "
            f"only {len(devices)} visible"
        )
    chosen = devices[:total]
    if not explicit_devices and total == len(devices):
        # Let mesh_utils lay the logical axes onto the physical ICI
        # topology (torus-neighbor rings per axis) instead of a flat
        # device-id reshape — on real multi-chip slices this is the
        # difference between collectives riding nearest-neighbor ICI
        # links and hopping across the torus. Only when the caller did
        # not pass an explicit device list (mesh_utils reorders, which
        # would silently discard a deliberate ordering); falls back to
        # the plain reshape off-TPU or for partial meshes.
        try:
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_device_mesh(
                tuple(sizes), devices=list(chosen)
            )
            return Mesh(arr, axis_names=names)
        except (
            ImportError,
            ValueError,
            NotImplementedError,
            # mesh_utils' TPU topology code bounds-checks with bare
            # asserts and raises RuntimeError on exotic slice shapes;
            # the flat reshape below is always a working layout.
            AssertionError,
            RuntimeError,
        ) as e:
            from elasticdl_tpu.common.log_utils import get_logger

            get_logger("parallel.mesh").warning(
                "Physical-topology mesh layout unavailable (%s); using "
                "flat device-id reshape — multi-chip collectives may "
                "cross non-neighbor ICI links", e,
            )
    return Mesh(chosen.reshape(sizes), axis_names=names)


def data_sharding(mesh: Mesh, axis=None) -> NamedSharding:
    """Leading-dim batch sharding over the data axis (plus the zero axis
    when the mesh factors data parallelism into two axes). Pass an explicit
    axis name or tuple to override."""
    if axis is None:
        axis = batch_axes(mesh) or DATA_AXIS
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_batch_to_multiple(batch, multiple):
    """Pad a numpy batch pytree's leading dim up to a multiple by cyclic
    repetition. Returns (padded_batch, real_n). The training step slices
    outputs back to real_n before the loss so padding rows never contribute
    gradient signal.
    """
    leaves = jax.tree_util.tree_leaves(batch)
    if not leaves:
        return batch, 0
    real_n = leaves[0].shape[0]
    padded_n = -(-real_n // multiple) * multiple
    if padded_n == real_n:
        return batch, real_n
    idx = np.arange(padded_n) % real_n
    padded = jax.tree_util.tree_map(
        lambda x: np.take(x, idx, axis=0), batch
    )
    return padded, real_n


# ---------------------------------------------------------------------------
# World spec: deterministic (config, topology) -> mesh resolution
# ---------------------------------------------------------------------------


class WorldTopology(NamedTuple):
    """The device shape of one world: everything mesh resolution may
    depend on. A speculating trainer builds topologies for worlds it is
    not in yet (e.g. the N-1-process world after a preemption)."""

    n_devices: int
    local_devices: int
    n_processes: int

    @staticmethod
    def current():
        return WorldTopology(
            n_devices=len(jax.devices()),
            local_devices=jax.local_device_count(),
            n_processes=jax.process_count(),
        )

    @property
    def multi_process(self):
        return self.n_processes > 1


class AxisDemand(NamedTuple):
    """One parallel feature's request for a mesh axis. Feature modules
    (zero1 / tensor_parallel / pipeline / ring_attention) construct
    these; the resolver composes them. `intra_process` demands must lie
    entirely inside one process's device slice in multi-process worlds —
    the composition invariant that keeps (variables, opt_state) fully
    addressable on every host for elastic regroup snapshots."""

    axis: str
    size: int
    intra_process: bool = True

    def infeasible_reason(self, topo: WorldTopology, trailing: int = 1):
        """Why this demand cannot be laid out on `topo` (None = it can).
        `trailing` is the product of other already-granted trailing-axis
        sizes it must co-divide with (e.g. model x seq)."""
        want = self.size * trailing
        if topo.n_devices % want:
            return (
                f"{self.axis} axis of {self.size} (x{trailing} trailing) "
                f"does not divide {topo.n_devices} devices"
            )
        if (
            self.intra_process
            and topo.multi_process
            and topo.local_devices % want
        ):
            return (
                f"{self.axis} axis of {self.size} (x{trailing} trailing) "
                f"does not divide the {topo.local_devices} local devices "
                f"of each process (intra-process axis)"
            )
        return None


class ParallelConfig(NamedTuple):
    """The trainer-config slice world resolution consumes. Hook PRESENCE
    is a bool (the hooks themselves stay on the trainer); `sp_suspended`
    carries the per-world ulysses/ring downgrade bit."""

    model_parallel: int = 1
    has_param_specs: bool = False
    zero1: bool = False
    pipeline_stages: int = 1
    has_pipeline_spec: bool = False
    context_parallel: int = 1
    has_context_parallel_model: bool = False
    sp_suspended: bool = False


class WorldSpec:
    """A resolved world: ordered mesh axes + which features are active.

    Hashable by `fingerprint()` — the identity the compile tracker, the
    speculative AOT store, and the regroup fast path all key on: two
    worlds with the same fingerprint compile byte-identical step
    programs, so a membership epoch bump that resolves to the same
    fingerprint needs NO re-lowering."""

    __slots__ = (
        "axes",
        "process_grouped",
        "topology",
        "tp",
        "sp",
        "pp",
        "zero1",
        "notes",
    )

    def __init__(self, axes, process_grouped, topology, tp=1, sp=1, pp=1,
                 zero1=False, notes=()):
        self.axes = tuple(axes)  # ((name, size), ...) ordered
        self.process_grouped = bool(process_grouped)
        self.topology = topology
        self.tp = tp
        self.sp = sp
        self.pp = pp
        self.zero1 = zero1
        self.notes = tuple(notes)

    def fingerprint(self):
        # Process structure is part of the program identity, not just
        # the axes: the compiled step branches on the process count
        # (loss slicing, buffer donation — single-process only), so an
        # 8-device/1-process and an 8-device/2-process pure-DP world
        # must NOT share a fingerprint even though their meshes match.
        body = ",".join(f"{name}={size}" for name, size in self.axes)
        if self.process_grouped:
            body += "|pg"
        if self.topology.n_processes > 1:
            body += f"|p{self.topology.n_processes}"
        return body

    def axis_sizes(self):
        return dict(self.axes)

    def __eq__(self, other):
        return (
            isinstance(other, WorldSpec)
            and self.fingerprint() == other.fingerprint()
        )

    def __hash__(self):
        return hash(self.fingerprint())

    def __repr__(self):
        return f"WorldSpec({self.fingerprint()})"

    def build_mesh(self) -> Mesh:
        """Materialize the spec on the live backend. The spec's device
        count may be a PREFIX of the visible devices (a speculated
        smaller world compiles over the surviving prefix of the current
        global device set)."""
        total = math.prod(s for _, s in self.axes)
        visible = jax.devices()
        if total > len(visible):
            raise ValueError(
                f"world spec {self.fingerprint()} wants {total} devices; "
                f"only {len(visible)} visible"
            )
        if self.process_grouped:
            return make_mesh(
                dict(self.axes),
                devices=process_grouped_devices()[:total],
            )
        if total == len(visible):
            # No explicit device list: make_mesh may then lay the axes
            # onto the physical ICI topology (torus-neighbor rings).
            return make_mesh(dict(self.axes))
        return make_mesh(dict(self.axes), devices=visible[:total])


def resolve_world_spec(
    config: ParallelConfig,
    topo: WorldTopology,
    param_check: Optional[Callable[[int], list]] = None,
) -> WorldSpec:
    """The one deterministic (config, topology) -> WorldSpec map.

    Precedence ladder (unchanged semantics from the pre-spec trainer):
    the stage axis excludes model/seq (both lay out the intra-process
    slice); seq drops before model when their product stops dividing;
    zero only factors pure multi-process DP. Every degrade lands in
    `spec.notes` as a human sentence — the trainer logs them, so the
    fallback behavior stays as loud as the ad-hoc ladder was.

    `param_check(mp) -> [violation messages]` lets the caller veto TP
    with knowledge the resolver lacks (live param shapes vs the model
    axis); resolution stays deterministic for a fixed check outcome.
    """
    notes = []
    n, local_n = topo.n_devices, topo.local_devices
    multi = topo.multi_process

    def _dp(extra_note=None):
        if extra_note:
            notes.append(extra_note)
        return WorldSpec(
            ((DATA_AXIS, n),), False, topo, notes=notes
        )

    pp = config.pipeline_stages
    if pp > 1 and config.has_pipeline_spec:
        from elasticdl_tpu.parallel.pipeline import stage_axis_demand

        demand = stage_axis_demand(pp)
        why = demand.infeasible_reason(topo)
        if why is None:
            return WorldSpec(
                ((DATA_AXIS, n // pp), (demand.axis, pp)),
                multi,
                topo,
                pp=pp,
                notes=notes,
            )
        notes.append(
            f"pipeline_stages {pp} infeasible on this world ({why}); "
            "running the staged model sequentially under pure data "
            "parallelism for this world"
        )
        return _dp()

    mp_eff = 1
    mp = config.model_parallel
    if mp > 1:
        if not config.has_param_specs:
            notes.append(
                f"model_parallel_size {mp} requested but the model spec "
                "has no param_specs hook; falling back to pure data "
                "parallelism"
            )
        else:
            from elasticdl_tpu.parallel.tensor_parallel import (
                model_axis_demand,
            )

            demand = model_axis_demand(mp)
            why = demand.infeasible_reason(topo)
            bad = param_check(mp) if param_check is not None and not why \
                else []
            if why is not None:
                notes.append(
                    f"model_parallel_size {mp} infeasible on this world "
                    f"({why}); falling back to pure data parallelism "
                    "for this world"
                )
            elif bad:
                notes.append(
                    f"param_specs incompatible with model_parallel_size "
                    f"{mp} ({'; '.join(bad[:3])}); falling back to pure "
                    "data parallelism"
                )
            else:
                mp_eff = mp

    sp_eff = 1
    sp = config.context_parallel
    if sp > 1 and config.has_context_parallel_model and not (
        config.sp_suspended
    ):
        from elasticdl_tpu.parallel.ring_attention import seq_axis_demand

        demand = seq_axis_demand(sp)
        why = demand.infeasible_reason(topo, trailing=mp_eff)
        if why is None:
            sp_eff = sp
        else:
            notes.append(
                f"context_parallel_size {sp} (x model_parallel "
                f"{mp_eff}) infeasible on this world ({why}); running "
                "without sequence parallelism for this world"
            )

    if mp_eff > 1 or sp_eff > 1:
        axes = [(DATA_AXIS, n // (mp_eff * sp_eff))]
        if mp_eff > 1:
            axes.append((MODEL_AXIS, mp_eff))
        if sp_eff > 1:
            axes.append((SEQ_AXIS, sp_eff))
        return WorldSpec(
            axes, multi, topo, tp=mp_eff, sp=sp_eff, notes=notes
        )

    if config.zero1 and multi and local_n > 1:
        from elasticdl_tpu.parallel.zero1 import zero_axis_demand

        demand = zero_axis_demand(local_n)
        if demand.infeasible_reason(topo) is None:
            # Factor pure DP into (data across processes, zero within):
            # the batch shards over both; optimizer state shards over
            # "zero" only, staying replicated across processes.
            return WorldSpec(
                ((DATA_AXIS, topo.n_processes), (demand.axis, local_n)),
                True,
                topo,
                zero1=True,
                notes=notes,
            )
    return _dp()


def shard_batch(batch, mesh: Mesh, axis=None):
    """Place a host batch onto the mesh, sharded along the data axis.

    Single-host: plain device_put. Multi-host (jax.process_count() > 1): each
    process holds its local slice of the global batch and contributes it via
    make_array_from_process_local_data — the global array's leading dim is
    world_batch = local_batch * num_processes.
    """
    sharding = data_sharding(mesh, axis)
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(sharding, x),
            batch,
        )
    return jax.device_put(batch, sharding)
