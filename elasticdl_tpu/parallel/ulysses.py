"""Ulysses-style all-to-all sequence parallelism.

Capability extension beyond the DP-only reference. Activations arrive
sequence-sharded [B, H, S_local, D]; an all-to-all over the sequence axis
re-shards them to head-sharded [B, H_local, S_global, D], where each device
runs FULL attention over the whole sequence for its head subset (flash
attention locally), and a second all-to-all restores sequence sharding.
Two all-to-alls per attention vs ring's N-1 ppermutes: better for moderate
sequence lengths when heads >= devices; ring wins when S_global's K/V
can't fit per device.
"""

import functools

import jax

from elasticdl_tpu.ops.flash_attention import flash_attention


def ulysses_attention(q, k, v, axis_name, attention_fn=None, causal=False):
    """Call INSIDE shard_map with q/k/v local blocks [B, H, S_local, D].
    Requires num_heads % axis_size == 0."""
    if attention_fn is None:
        # Flash attention by default: the whole point of the re-shard is
        # attending over S_global, and a full score matrix there is the
        # quadratic memory this path exists to avoid.
        attention_fn = functools.partial(flash_attention, causal=causal)
    axis_size = jax.lax.psum(1, axis_name)
    h = q.shape[1]
    if h % axis_size:
        raise ValueError(
            f"ulysses needs heads ({h}) divisible by the seq axis "
            f"({axis_size})"
        )

    def seq_to_heads(x):
        # [B, H, S_local, D] -> [B, H/N, S_global, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    def heads_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    out = attention_fn(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v))
    return heads_to_seq(out)


def make_ulysses_attention(
    mesh, axis_name="seq", attention_fn=None, causal=False,
    batch_axis=None,
):
    """shard_map-wrapped Ulysses attention over GLOBAL [B, H, S, D] arrays
    sharded on S (and optionally on B along `batch_axis`)."""
    from jax.sharding import PartitionSpec as P
    from elasticdl_tpu.common.jax_compat import shard_map

    spec = P(batch_axis, None, axis_name, None)
    return shard_map(
        functools.partial(
            ulysses_attention,
            axis_name=axis_name,
            attention_fn=attention_fn,
            causal=causal,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
