"""Cross-replica weight-update sharding (ZeRO-1) for data parallelism.

Technique from "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arXiv:2004.13336, see PAPERS.md): in pure DP the
optimizer state is bitwise-identical on every replica, so storing and
updating it everywhere wastes HBM (Adam doubles the param bytes) and
VPU time. Sharding each optimizer-state leaf over the data axis makes
GSPMD compile the update as reduce-scatter(grads) -> shard-local
optimizer math -> all-gather(updated params) — the collectives ride ICI
and the per-chip optimizer memory drops by the axis size.

Expressed entirely as PartitionSpecs fed to jit in_shardings/out_shardings
(the XLA-native way): leaves whose leading dim divides the axis shard on
dim 0, everything else (scalar step counts, ragged leaves) replicates.
"""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from elasticdl_tpu.parallel.mesh import ZERO_AXIS, AxisDemand


def zero_axis_demand(local_devices):
    """ZeRO-1's mesh-axis contribution to world resolution: an
    intra-process "zero" axis over each host's local device slice, so
    optimizer shards die with nothing when a PEER process dies (every
    host keeps a fully-addressable copy for regroup snapshots)."""
    return AxisDemand(ZERO_AXIS, int(local_devices), intra_process=True)


def weight_update_specs(opt_state, mesh, axis="data"):
    """PartitionSpec pytree for an optax state: dim-0 sharding over `axis`
    for every leaf that divides evenly, P() otherwise."""
    n = mesh.shape[axis]

    def spec(leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) >= 1 and shape[0] and shape[0] % n == 0:
            return P(axis)
        return P()

    return jax.tree_util.tree_map(spec, opt_state)


def weight_update_shardings(opt_state, mesh, axis="data"):
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        weight_update_specs(opt_state, mesh, axis),
        is_leaf=lambda v: isinstance(v, P),
    )
