"""Static schedule tables for interleaved 1F1B pipeline parallelism.

Megatron-style interleaving (virtual pipeline stages): the layer stack is
split into n_stages * v chunks, device d hosting chunks {d, d+N, d+2N, ...}
— so a microbatch's chunk-to-chunk hops are ALWAYS to the next device in
the ring, and the warmup/drain bubble shrinks by ~v because a device can
start chunk r+1 work while chunk r's later microbatches are still
upstream.

Everything is decided AHEAD of compile: a greedy list-scheduler walks the
F(c,m)/B(c,m) dependency DAG (fwd needs the previous chunk's output from
an earlier tick; bwd needs the next chunk's gradient from an earlier tick
plus its own stashed input; the last chunk's bwd may share its fwd's
tick) and emits per-(tick, device) slot tables that the Pallas-free scan
kernel (pipeline.py's interleaved variant) consumes as data. Ticks are
PAIRED slots — one fwd + one bwd per device per tick — matching the 1F1B
steady state where a device alternates F and B at full utilization.

The scheduler also sizes the runtime buffers exactly: mailbox slots for
in-flight messages (tagged by global slot id modulo capacity, collision-
checked here) and the per-chunk input stash depth.
"""

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class InterleavedSchedule:
    n_stages: int
    v: int  # chunks per device
    num_micro: int
    ticks: int
    # [ticks, n_stages] int32 tables; -1 = idle slot. Chunk indices are
    # GLOBAL (0..n*v-1); the kernel derives the local param row c // n.
    fwd_chunk: np.ndarray
    fwd_micro: np.ndarray
    bwd_chunk: np.ndarray
    bwd_micro: np.ndarray
    # [ticks] int32: microbatch whose LAST-chunk forward completes this
    # tick (-1 = none) — drives the vocab-parallel head on every device.
    head_micro: np.ndarray
    # Exact runtime buffer sizes derived from the committed schedule.
    fwd_mailbox: int
    bwd_mailbox: int
    stash_depth: int
    dy_store: int  # last-chunk dy slots (head tick -> its bwd tick)

    @property
    def total_chunks(self):
        return self.n_stages * self.v


def build_interleaved_schedule(n_stages, v, num_micro):
    """Greedy paired-slot schedule. Deterministic; O(ticks * chunks)."""
    n, m_total = n_stages, num_micro
    total = n * v
    f_done = -np.ones((total, m_total), np.int64)  # tick fwd completed
    b_done = -np.ones((total, m_total), np.int64)

    def device_of(c):
        return c % n

    fwd_rows, bwd_rows = [], []
    fm_rows, bm_rows = [], []
    t = 0
    # Safety valve well above any legal schedule length.
    max_ticks = 4 * v * (m_total + 2 * n)
    while (f_done < 0).any() or (b_done < 0).any():
        if t >= max_ticks:
            raise RuntimeError(
                f"interleaved scheduler did not converge "
                f"(N={n}, v={v}, M={m_total})"
            )
        fwd_row = -np.ones(n, np.int64)
        fm_row = -np.ones(n, np.int64)
        bwd_row = -np.ones(n, np.int64)
        bm_row = -np.ones(n, np.int64)
        # ---- fwd slots: ready = prev chunk done at an EARLIER tick ----
        for d in range(n):
            best = None
            for c in range(d, total, n):
                for m in range(m_total):
                    if f_done[c, m] >= 0:
                        continue
                    if c > 0 and not (0 <= f_done[c - 1, m] < t):
                        continue
                    # Megatron interleaved order: cycle chunks in
                    # microbatch GROUPS of N (device d runs chunk r for N
                    # microbatches, then chunk r+1 for the same group...)
                    # — this is what lets later chunks start while the
                    # group's peers are still upstream, shrinking warmup
                    # by ~v.
                    key = (m // n, c, m)
                    if best is None or key < best[0]:
                        best = (key, c, m)
                    break  # first undone m for this chunk is the candidate
            if best is not None:
                _, c, m = best
                fwd_row[d] = c
                fm_row[d] = m
        # ---- bwd slots: ready = next chunk's bwd done earlier AND own
        # fwd done (same tick allowed only for the LAST chunk, whose dy
        # is produced by the fwd slot just above it) ----
        for d in range(n):
            best = None
            for c in range(d, total, n):
                for m in range(m_total):
                    if b_done[c, m] >= 0:
                        continue
                    if c == total - 1:
                        own_f = f_done[c, m]
                        # Set this tick by the fwd row above?
                        if own_f < 0 and fwd_row[d] == c and fm_row[d] == m:
                            own_f = t
                        if not (0 <= own_f <= t):
                            continue
                    else:
                        if not (0 <= f_done[c, m] < t):
                            continue
                        if not (0 <= b_done[c + 1, m] < t):
                            continue
                    # Mirror of the fwd order: drain deepest chunks of
                    # the oldest microbatch group first.
                    key = (m // n, -c, m)
                    if best is None or key < best[0]:
                        best = (key, c, m)
                    break
            if best is not None:
                _, c, m = best
                bwd_row[d] = c
                bm_row[d] = m
        # Commit the tick.
        for d in range(n):
            if fwd_row[d] >= 0:
                f_done[fwd_row[d], fm_row[d]] = t
            if bwd_row[d] >= 0:
                b_done[bwd_row[d], bm_row[d]] = t
        fwd_rows.append(fwd_row)
        fm_rows.append(fm_row)
        bwd_rows.append(bwd_row)
        bm_rows.append(bm_row)
        t += 1

    ticks = t
    fwd_chunk = np.stack(fwd_rows)
    fwd_micro = np.stack(fm_rows)
    bwd_chunk = np.stack(bwd_rows)
    bwd_micro = np.stack(bm_rows)
    last_dev = device_of(total - 1)
    head_micro = np.where(
        fwd_chunk[:, last_dev] == total - 1,
        fwd_micro[:, last_dev],
        -1,
    )

    # ---- buffer sizing (exact, from the committed schedule) ----
    # fwd message for F(c,m) (c>0): sent end of f_done[c-1,m], consumed
    # at f_done[c,m]; in the mailbox during (send, consume]. Tag id =
    # c*m_total + m; capacity must avoid two LIVE messages sharing
    # id % capacity at the same receiving device.
    def size_mailbox(producer_done, consumer_done, pairs):
        cap = 1
        while True:
            ok = True
            live = {}
            for (c, m) in pairs:
                send = producer_done(c, m)
                recv = consumer_done(c, m)
                tag = (c * m_total + m) % cap
                dev = device_of(c)
                live.setdefault((dev, tag), []).append((send, recv))
            for intervals in live.values():
                intervals.sort()
                for (s1, r1), (s2, r2) in zip(intervals, intervals[1:]):
                    if s2 < r1:  # overlapping lifetimes share a slot
                        ok = False
                        break
                if not ok:
                    break
            if ok:
                return cap
            cap += 1

    fwd_pairs = [
        (c, m) for c in range(1, total) for m in range(m_total)
    ]
    bwd_pairs = [
        (c, m) for c in range(0, total - 1) for m in range(m_total)
    ]
    fwd_mailbox = size_mailbox(
        lambda c, m: f_done[c - 1, m], lambda c, m: f_done[c, m],
        fwd_pairs,
    )
    bwd_mailbox = size_mailbox(
        lambda c, m: b_done[c + 1, m], lambda c, m: b_done[c, m],
        bwd_pairs,
    )
    # Stash: input of F(c,m) lives until B(c,m); per local chunk, keyed by
    # m % depth. Sized by the same exact modulo-collision check as the
    # mailboxes/dy_store — not a max-overlap heuristic, whose sufficiency
    # would silently depend on the scheduler processing each chunk's
    # microbatches strictly in order. Inclusive same-tick rule: the fwd
    # write of one microbatch and the bwd read of another land mid-tick,
    # so a shared slot on the same tick is a collision.
    def _stash_collides(depth):
        for c in range(total):
            by_slot = {}
            for m in range(m_total):
                by_slot.setdefault(m % depth, []).append(
                    (f_done[c, m], b_done[c, m])
                )
            for intervals in by_slot.values():
                intervals.sort()
                for (s1, r1), (s2, r2) in zip(intervals, intervals[1:]):
                    if s2 <= r1:
                        return True
        return False

    depth = 1
    while _stash_collides(depth):
        depth += 1
    # dy for the last chunk's bwd: produced by the head at the last
    # chunk's fwd tick, consumed at its bwd tick (same tick allowed);
    # keyed m % dy_store.
    dy_cap = 1
    c_last = total - 1
    while True:
        ok = True
        by_slot = {}
        for m in range(m_total):
            by_slot.setdefault(m % dy_cap, []).append(
                (f_done[c_last, m], b_done[c_last, m])
            )
        for intervals in by_slot.values():
            intervals.sort()
            for (s1, r1), (s2, r2) in zip(intervals, intervals[1:]):
                # The kernel writes dy MID-tick (head slot) before the
                # bwd read, so a same-tick produce/consume pair on one
                # slot would overwrite first: inclusive overlap, unlike
                # the end-of-tick mailbox writes above.
                if s2 <= r1:
                    ok = False
                    break
            if not ok:
                break
        if ok:
            break
        dy_cap += 1
    return InterleavedSchedule(
        n_stages=n,
        v=v,
        num_micro=m_total,
        ticks=ticks,
        fwd_chunk=fwd_chunk.astype(np.int32),
        fwd_micro=fwd_micro.astype(np.int32),
        bwd_chunk=bwd_chunk.astype(np.int32),
        bwd_micro=bwd_micro.astype(np.int32),
        head_micro=head_micro.astype(np.int32),
        fwd_mailbox=int(fwd_mailbox),
        bwd_mailbox=int(bwd_mailbox),
        stash_depth=int(depth),
        dy_store=int(dy_cap),
    )
