"""Tensor parallelism for the transformer flagship: GSPMD sharding rules.

No reference counterpart (the reference is DP-only, SURVEY.md §2.10); this
extends the parallel story beyond DP+sequence parallelism. TPU-first: no
manual collectives — the Megatron-style layout is expressed purely as
PartitionSpecs on the param tree (attention heads and the MLP hidden
dimension column-split over the "model" mesh axis, their consumers
row-split, vocab split on the embedding/lm head) and `jit` with
`in_shardings` lets XLA insert the all-reduces over ICI. Composes with
batch sharding over "data" on the same mesh.
"""

import re

from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.pytree_utils import nest_at, walk_dict
from elasticdl_tpu.parallel.mesh import MODEL_AXIS, AxisDemand


def model_axis_demand(model_parallel):
    """Tensor parallelism's mesh-axis contribution to world resolution:
    an intra-process "model" axis (TP collectives ride on-host ICI and
    params stay fully addressable for elastic regroup snapshots)."""
    return AxisDemand(MODEL_AXIS, int(model_parallel), intra_process=True)

# (path regex, spec) — first match wins; default replicated. Param shapes:
#   qkv/kernel  [D, 3, H, Dh]   heads column-split
#   qkv/bias       [3, H, Dh]
#   proj/kernel [D, D]          row-split (input dim = concat of heads)
#   Dense_0     [D, 4D]         MLP up, column-split
#   Dense_1     [4D, D]         MLP down, row-split
#   tok_emb     [V, D]          vocab-split
#   lm_head     [D, V]          vocab column-split
_RULES = (
    (r".*/qkv/kernel$", lambda ax: P(None, None, ax, None)),
    (r".*/qkv/bias$", lambda ax: P(None, ax, None)),
    (r".*/proj/kernel$", lambda ax: P(ax, None)),
    (r".*/Dense_0/kernel$", lambda ax: P(None, ax)),
    (r".*/Dense_0/bias$", lambda ax: P(ax)),
    (r".*/Dense_1/kernel$", lambda ax: P(ax, None)),
    (r"(^|.*/)tok_emb/embedding$", lambda ax: P(ax, None)),
    (r"(^|.*/)lm_head/kernel$", lambda ax: P(None, ax)),
    (r"(^|.*/)lm_head/bias$", lambda ax: P(ax)),
)


def transformer_param_specs(params, model_axis="model"):
    """Param pytree -> matching PartitionSpec pytree (Megatron layout over
    `model_axis`; everything unmatched — LayerNorms, proj/Dense_1 biases,
    pos_emb — replicated)."""
    specs = {}
    for path, _ in walk_dict(params):
        joined = "/".join(path)
        spec = P()
        for pattern, make in _RULES:
            if re.match(pattern, joined):
                spec = make(model_axis)
                break
        specs[path] = spec
    return nest_at(specs)


def validate_divisibility(config, model_parallel):
    """TP requires the split dimensions to divide evenly."""
    if config.n_heads % model_parallel:
        raise ValueError(
            f"n_heads {config.n_heads} not divisible by model-parallel "
            f"size {model_parallel}"
        )
    if (4 * config.d_model) % model_parallel:
        raise ValueError(
            f"MLP hidden dim d_model*4 ({4 * config.d_model}) not "
            f"divisible by model-parallel size {model_parallel}"
        )
    if config.vocab % model_parallel:
        raise ValueError(
            f"vocab ({config.vocab}) not divisible by model-parallel "
            f"size {model_parallel}"
        )
