"""Quantized cross-replica gradient reduction (EQuARX-style).

Technique from "EQuARX: Efficient Quantized AllReduce in XLA"
(arXiv:2506.17615, PAPERS.md): a ring/BiDir allreduce moves every gradient
byte across ICI/DCN twice, so quantizing the wire payload to int8 with
per-block scales cuts the collective's bandwidth ~4x at a bounded,
stochastic-noise-sized error — the lever that matters when DP gradients
cross DCN (multislice) rather than ICI.

XLA's own allreduce lowering is not reachable from JAX user code, so the
transform is expressed with the collectives that ARE: inside `shard_map`,

    all_to_all(int8 blocks + f32 scales)   # each replica scatters its
                                           # quantized shard contributions
    local dequantize + sum (f32)           # exact accumulation
    all_gather(int8 of the reduced shard)  # quantized again for the
                                           # return trip

which is exactly the reduce-scatter + all-gather decomposition of a ring
allreduce with both wire legs quantized. Use `quantized_pmean` in
shard_map-formulated DP steps; the GSPMD jit path keeps XLA's f32
collectives (its allreduce is compiler-inserted and not user-swappable).
"""

import jax
import jax.numpy as jnp

_EPS = 1e-12


def _quantize(x):
    """f32 [n, ...] -> (int8 [n, ...], f32 per-row scales [n, 1...])
    symmetric max-abs quantization per leading-dim block."""
    absmax = jnp.max(
        jnp.abs(x), axis=tuple(range(1, x.ndim)), keepdims=True
    )
    scale = absmax / 127.0 + _EPS
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _dequantize(q, scale):
    return q.astype(jnp.float32) * scale


def quantized_psum_1d(x, axis_name):
    """Allreduce-sum a flat f32 [L] vector over `axis_name` with int8 wire
    payloads (the axis size must divide L: the reshape below splits x into
    one block per replica). Call inside shard_map."""
    n = jax.lax.psum(1, axis_name)
    blocks = x.reshape(n, -1)  # block b is replica b's return shard
    q, scale = _quantize(blocks)
    # Leg 1 (reduce-scatter): replica r receives every replica's
    # quantized block r, dequantizes, and sums exactly in f32.
    q_t = jax.lax.all_to_all(
        q[:, None], axis_name, split_axis=0, concat_axis=1
    )  # [1, n, block] -> local [n, block] contributions for MY shard
    s_t = jax.lax.all_to_all(
        scale[:, None], axis_name, split_axis=0, concat_axis=1
    )
    mine = jnp.sum(_dequantize(q_t[0], s_t[0]), axis=0)  # [block]
    # Leg 2 (all-gather): my reduced shard goes back quantized.
    qm, sm = _quantize(mine[None])
    gathered_q = jax.lax.all_gather(qm[0], axis_name)  # [n, block]
    gathered_s = jax.lax.all_gather(sm[0], axis_name)  # [n, 1]
    return _dequantize(gathered_q, gathered_s).reshape(-1)


def quantized_pmean(tree, axis_name):
    """Mean-reduce a gradient pytree over `axis_name` with int8 wire
    payloads. Leaves are flattened into one vector (padded up to the axis
    size) so the per-block scales cover contiguous ranges, then restored.
    Error is bounded by the per-block max-abs / 127 rounding step — the
    magnitude of stochastic-rounding noise, not a bias."""
    n = jax.lax.psum(1, axis_name)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sizes = [leaf.size for leaf in leaves]
    flat = jnp.concatenate(
        [leaf.reshape(-1).astype(jnp.float32) for leaf in leaves]
    )
    total = flat.size
    padded = -(-total // n) * n
    if padded != total:
        flat = jnp.concatenate(
            [flat, jnp.zeros(padded - total, jnp.float32)]
        )
    summed = quantized_psum_1d(flat, axis_name) / n
    out = []
    offset = 0
    for leaf, size in zip(leaves, sizes):
        out.append(
            summed[offset:offset + size].reshape(leaf.shape).astype(
                leaf.dtype
            )
        )
        offset += size
    return jax.tree_util.tree_unflatten(treedef, out)
