"""Quantized cross-replica gradient reduction (EQuARX-style).

Technique from "EQuARX: Efficient Quantized AllReduce in XLA"
(arXiv:2506.17615, PAPERS.md): a ring/BiDir allreduce moves every gradient
byte across ICI/DCN twice, so quantizing the wire payload to int8 with
per-block scales cuts the collective's bandwidth ~4x at a bounded,
stochastic-noise-sized error — the lever that matters when DP gradients
cross DCN (multislice) rather than ICI.

XLA's own allreduce lowering is not reachable from JAX user code, so the
transform is expressed with the collectives that ARE: inside `shard_map`,

    all_to_all(int8 blocks + f32 scales)   # each replica scatters its
                                           # quantized shard contributions
    local dequantize + sum (f32)           # exact accumulation
    all_gather(int8 of the reduced shard)  # quantized again for the
                                           # return trip

which is exactly the reduce-scatter + all-gather decomposition of a ring
allreduce with both wire legs quantized. Use `quantized_pmean` in
shard_map-formulated DP steps; the GSPMD jit path keeps XLA's f32
collectives (its allreduce is compiler-inserted and not user-swappable).

PARTIAL-AUTO CAVEAT: when shard_map goes manual over the data axis only
(the DP x TP composition — the model axis stays automatic so GSPMD keeps
the Megatron collectives), XLA's SPMD partitioner cannot partition
`all_to_all`/`all_gather` in the manual subgroup (fatal
`IsManualSubgroup` check, observed through jax 0.4.x) — only the
psum/pmax allreduce family survives. `quantized_pmean(...,
collectives="psum_lanes")` reformulates for that regime: a shared
per-block scale (one f32 pmax), int8-grid rounding, and ONE psum whose
lanes are int16 (int32 past axis size 258) carrying the quantized
values — 2 bytes on the wire per element instead of f32's 4, one
quantization instead of two (the shared scale makes the sum exact on
the int8 grid, so there is no second rounding on the return leg).
"""

import jax
import jax.numpy as jnp

_EPS = 1e-12


def _quantize(x):
    """f32 [n, ...] -> (int8 [n, ...], f32 per-row scales [n, 1...])
    symmetric max-abs quantization per leading-dim block."""
    absmax = jnp.max(
        jnp.abs(x), axis=tuple(range(1, x.ndim)), keepdims=True
    )
    scale = absmax / 127.0 + _EPS
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _dequantize(q, scale):
    return q.astype(jnp.float32) * scale


def quantized_psum_1d(x, axis_name):
    """Allreduce-sum a flat f32 [L] vector over `axis_name` with int8 wire
    payloads (the axis size must divide L: the reshape below splits x into
    one block per replica). Call inside shard_map."""
    n = jax.lax.psum(1, axis_name)
    blocks = x.reshape(n, -1)  # block b is replica b's return shard
    q, scale = _quantize(blocks)
    # Leg 1 (reduce-scatter): replica r receives every replica's
    # quantized block r, dequantizes, and sums exactly in f32.
    q_t = jax.lax.all_to_all(
        q[:, None], axis_name, split_axis=0, concat_axis=1
    )  # [1, n, block] -> local [n, block] contributions for MY shard
    s_t = jax.lax.all_to_all(
        scale[:, None], axis_name, split_axis=0, concat_axis=1
    )
    mine = jnp.sum(_dequantize(q_t[0], s_t[0]), axis=0)  # [block]
    # Leg 2 (all-gather): my reduced shard goes back quantized.
    qm, sm = _quantize(mine[None])
    gathered_q = jax.lax.all_gather(qm[0], axis_name)  # [n, block]
    gathered_s = jax.lax.all_gather(sm[0], axis_name)  # [n, 1]
    return _dequantize(gathered_q, gathered_s).reshape(-1)


def quantized_psum_lanes_1d(x, axis_name):
    """Allreduce-sum a flat f32 [L] vector over `axis_name` using ONLY
    psum/pmax collectives — the family the SPMD partitioner can handle
    inside a PARTIAL-auto shard_map (see the module docstring's caveat).

    One shared per-block scale travels as an f32 pmax; values round onto
    the int8 grid and sum in int16 lanes (int32 once 127 * axis_size
    would overflow int16). Because every replica quantizes with the SAME
    scale, the summed grid values dequantize exactly: a single rounding
    per element, where the all_to_all path rounds twice."""
    n = jax.lax.psum(1, axis_name)
    blocks = x.reshape(n, -1)  # same blocking granularity as above
    absmax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scale = jax.lax.pmax(absmax, axis_name) / 127.0 + _EPS
    q = jnp.clip(jnp.round(blocks / scale), -127, 127)
    lanes = jnp.int16 if 127 * n <= 32767 else jnp.int32
    summed = jax.lax.psum(q.astype(lanes), axis_name)
    return (summed.astype(jnp.float32) * scale).reshape(-1)


def quantized_pmean(tree, axis_name, collectives="all_to_all"):
    """Mean-reduce a gradient pytree over `axis_name` with int8 wire
    payloads. Leaves are flattened into one vector (padded up to the axis
    size) so the per-block scales cover contiguous ranges, then restored.
    Error is bounded by the per-block max-abs / 127 rounding step — the
    magnitude of stochastic-rounding noise, not a bias.

    collectives="all_to_all" is the full reduce-scatter/all-gather wire
    (1 int8 byte per element per leg); "psum_lanes" is the partial-auto
    safe formulation (2 bytes per element, single rounding) required when
    the surrounding shard_map keeps other mesh axes automatic."""
    n = jax.lax.psum(1, axis_name)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sizes = [leaf.size for leaf in leaves]
    flat = jnp.concatenate(
        [leaf.reshape(-1).astype(jnp.float32) for leaf in leaves]
    )
    total = flat.size
    padded = -(-total // n) * n
    if padded != total:
        flat = jnp.concatenate(
            [flat, jnp.zeros(padded - total, jnp.float32)]
        )
    reduce = (
        quantized_psum_lanes_1d
        if collectives == "psum_lanes"
        else quantized_psum_1d
    )
    summed = reduce(flat, axis_name) / n
    out = []
    offset = 0
    for leaf, size in zip(leaves, sizes):
        out.append(
            summed[offset:offset + size].reshape(leaf.shape).astype(
                leaf.dtype
            )
        )
        offset += size
    return jax.tree_util.tree_unflatten(treedef, out)
