"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

Capability extension beyond the reference (which is DP-only; SURVEY.md §2.10
records no TP/PP/SP/EP anywhere upstream). TPU-first design: per-stage
parameters are STACKED along a leading axis and sharded over the "stage"
mesh axis, so each device owns exactly one stage's weights. The whole
pipeline — fills, steady state, and drain — is ONE `lax.scan` over
`num_microbatches + num_stages - 1` ticks inside `shard_map`: at every tick
each device runs its stage on the activation received from its neighbor on
the previous tick (`lax.ppermute` ring shift), stage 0 feeding fresh
microbatches and the last stage banking finished ones. Differentiating
through the scan + ppermute yields the mirrored backward schedule
automatically, and XLA compiles the full fwd+bwd pipeline (bubble included)
into a single SPMD program whose stage hops ride ICI.

Why this shape and not a Python loop of per-stage jits: under jit the scan
is traced once with static shapes, collectives are neighbor-only
ppermutes (no host round-trips between microbatches), and the bubble cost
is the schedule's only overhead — (N-1)/(M+N-1) of ticks idle per device,
amortized by raising M.

Memory: scan autodiff saves each tick's activations; with `remat=True` the
stage body is wrapped in `jax.checkpoint`, storing only the inter-stage
activations (O(M) per device) and recomputing block internals — the same
recipe the flagship LM uses for long context.

Composes with data parallelism: on a ("data", "stage") mesh the microbatch
batch dim is sharded over "data" while params shard over "stage"; every
collective here names only the stage axis.
"""

import collections

import jax
import jax.numpy as jnp

# What a model spec's `pipeline_spec(...)` hook hands the AllReduce trainer
# (worker --pipeline_stages; the stage-hook twin of the param_specs hook):
#   init_fn(rng, sample_features) -> params        (staged param tree)
#   loss_and_grads_fn(params, features, labels, rng=None) -> (loss, grads)
#       the scheduled training step; call inside jit on a mesh whose
#       "stage" axis matches the build
#   apply_fn(params, features, training=False, rngs=None) -> outputs
#       schedule-free forward over the SAME param tree, valid on any mesh
#       (no stage axis needed) — evaluation/prediction, and the trainer's
#       sequential pure-DP fallback when a world can't host the stage axis
#   param_specs_fn(params) -> PartitionSpec tree for the staged params
PipelineBuild = collections.namedtuple(
    "PipelineBuild",
    ["init_fn", "loss_and_grads_fn", "apply_fn", "param_specs_fn"],
)


def stage_axis_demand(n_stages):
    """Pipelining's mesh-axis contribution to world resolution: an
    intra-process "stage" axis (stage hops ride on-host ICI; every host
    keeps the whole staged tree addressable for regroup snapshots). The
    resolver gives the stage axis precedence and excludes model/seq —
    all three lay out the same intra-process device slice."""
    from elasticdl_tpu.parallel.mesh import STAGE_AXIS, AxisDemand

    return AxisDemand(STAGE_AXIS, int(n_stages), intra_process=True)


def pipeline_apply(stage_fn, stage_params, x_micro, axis_name="stage",
                   rng=None, batch_axis=None):
    """Run microbatches through the pipeline. Call INSIDE shard_map.

    stage_fn: (params_for_one_stage, x_microbatch) -> y_microbatch, with
      output shaped like the input (the inter-stage activation contract).
      When `rng` is given, called as (params, x, tick_rng) instead, with
      tick_rng distinct per (stage, tick, data-shard) — fold_in of the
      stage index, tick counter, and (when `batch_axis` names a DP mesh
      axis) the data-shard index — so stochastic layers (dropout) draw
      independent bits per stage, microbatch, and batch shard.
    stage_params: pytree whose leaves have a leading stage axis; sharded
      over `axis_name`, so inside shard_map the local leading dim is 1.
    x_micro: [M, mb, ...] microbatched input, replicated over `axis_name`.
    Returns [M, mb, ...] outputs, replicated over `axis_name` (the last
    stage's results are broadcast with a masked psum).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    params_local = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    num_micro = x_micro.shape[0]
    ticks = num_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 consumes fresh microbatch t during the fill; other
        # stages consume what arrived from their neighbor last tick.
        fresh = jax.lax.dynamic_index_in_dim(
            x_micro, jnp.minimum(t, num_micro - 1), 0, keepdims=False
        )
        inp = jnp.where(stage == 0, fresh, state)
        if rng is None:
            out = stage_fn(params_local, inp)
        else:
            tick_rng = jax.random.fold_in(
                jax.random.fold_in(rng, stage), t
            )
            if batch_axis is not None:
                # rng enters shard_map replicated; without this fold the
                # same dropout mask would repeat across every DP shard.
                tick_rng = jax.random.fold_in(
                    tick_rng, jax.lax.axis_index(batch_axis)
                )
            out = stage_fn(params_local, inp, tick_rng)
        # The last stage banks microbatch t-(N-1) once the pipe is full.
        out_idx = t - (n_stages - 1)
        bank = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
        safe = jnp.clip(out_idx, 0, num_micro - 1)
        cur = jax.lax.dynamic_index_in_dim(outputs, safe, 0,
                                           keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(bank, out, cur), safe, 0
        )
        state = jax.lax.ppermute(out, axis_name, perm)
        return (state, outputs), None

    state0 = jnp.zeros_like(x_micro[0])
    outputs0 = jnp.zeros_like(x_micro)
    (_, outputs), _ = jax.lax.scan(
        tick, (state0, outputs0), jnp.arange(ticks)
    )
    # Broadcast the last stage's banked outputs to every stage so the
    # result is replicated over the pipeline axis.
    return jax.lax.psum(
        jnp.where(stage == n_stages - 1, outputs, 0), axis_name
    )


def make_pipeline(stage_fn, mesh, axis_name="stage", batch_axis=None,
                  remat=False, remat_policy=None):
    """shard_map-wrapped pipeline: takes GLOBAL (stage_params, x_micro)
    with params stacked [n_stages, ...] (sharded over `axis_name`) and
    x_micro [M, mb, ...] (optionally sharded over `batch_axis` on mb for
    DP x PP meshes); returns [M, mb, ...] outputs with x's sharding."""
    from jax.sharding import PartitionSpec as P
    from elasticdl_tpu.common.jax_compat import shard_map

    if remat:
        kwargs = {}
        if remat_policy:
            kwargs["policy"] = getattr(
                jax.checkpoint_policies, remat_policy
            )
        stage_fn = jax.checkpoint(stage_fn, **kwargs)
    x_spec = P(None, batch_axis)

    def _validate(stage_params, x_micro):
        # Fail with actionable messages instead of shard_map internals.
        n_stages = mesh.shape[axis_name]
        lead = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
        if lead != n_stages:
            raise ValueError(
                f"stage_params leading dim {lead} != mesh axis "
                f"{axis_name!r} size {n_stages}"
            )
        if batch_axis is not None:
            dp = mesh.shape[batch_axis]
            mb = x_micro.shape[1]
            if mb % dp:
                raise ValueError(
                    f"microbatch size {mb} not divisible by "
                    f"{batch_axis!r} axis size {dp}; adjust the batch "
                    f"size or num_microbatches"
                )

    def wrapper(stage_params, x_micro, rng=None):
        _validate(stage_params, x_micro)
        p_specs = jax.tree_util.tree_map(
            lambda _: P(axis_name), stage_params
        )
        if rng is None:
            def run(stage_params, x_micro):
                return pipeline_apply(
                    stage_fn, stage_params, x_micro, axis_name=axis_name
                )

            return shard_map(
                run,
                mesh=mesh,
                in_specs=(p_specs, x_spec),
                out_specs=x_spec,
                check_vma=False,
            )(stage_params, x_micro)

        def run_rng(stage_params, x_micro, rng):
            return pipeline_apply(
                stage_fn, stage_params, x_micro, axis_name=axis_name,
                rng=rng, batch_axis=batch_axis,
            )

        return shard_map(
            run_rng,
            mesh=mesh,
            in_specs=(p_specs, x_spec, P()),
            out_specs=x_spec,
            check_vma=False,
        )(stage_params, x_micro, rng)

    return wrapper


def microbatch(x, num_microbatches):
    """[B, ...] -> [M, B//M, ...]; B must divide evenly."""
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} not divisible by {num_microbatches} microbatches"
        )
    return x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])


def unmicrobatch(y):
    """[M, mb, ...] -> [M*mb, ...]."""
    return y.reshape(y.shape[0] * y.shape[1], *y.shape[2:])


def stack_stage_params(per_stage):
    """List of per-stage param pytrees -> one pytree with a leading stage
    axis (what pipeline_apply expects, sharded P('stage', ...))."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage
    )


# ---------- pipelined transformer LM (flagship integration) ----------


def make_lm_pipeline(cfg, mesh, n_stages, num_microbatches,
                     axis_name="stage", batch_axis=None):
    """A pipelined build of the flagship transformer LM: embedding and LM
    head run replicated over the stage axis (they are a small fraction of
    the FLOPs), the Block stack is split into `n_stages` equal stages and
    pipelined. Returns (init_fn, apply_fn):

      init_fn(rng, sample_tokens) -> params
          {"embed": ..., "stages": stacked [n_stages, ...], "head": ...}
      apply_fn(params, tokens, training=False) -> [B, S, vocab] logits
    """
    import flax.linen as nn

    from elasticdl_tpu.models.transformer.transformer_lm import (
        Block,
        embed_input,
        head_output,
    )

    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by {n_stages} stages"
        )
    layers_per_stage = cfg.n_layers // n_stages

    # Thin module shells around the SAME embed/head implementations the
    # monolithic TransformerLM uses (transformer_lm.embed_input /
    # head_output) — the only pipeline-specific structure is the stage
    # grouping of Blocks.
    class EmbedIn(nn.Module):
        @nn.compact
        def __call__(self, tokens):
            return embed_input(cfg, tokens)

    class Stage(nn.Module):
        @nn.compact
        def __call__(self, x, training=False):
            for _ in range(layers_per_stage):
                x = Block(cfg)(x, training)
            return x

    class HeadOut(nn.Module):
        @nn.compact
        def __call__(self, x):
            return head_output(cfg, x)

    embed_mod, stage_mod, head_mod = EmbedIn(), Stage(), HeadOut()

    def init_fn(rng, sample_tokens):
        r_embed, r_stage, r_head = jax.random.split(rng, 3)
        embed_p = embed_mod.init(r_embed, sample_tokens)["params"]
        sample_x = embed_mod.apply({"params": embed_p}, sample_tokens)
        mb = sample_x[: max(1, sample_x.shape[0] // num_microbatches)]
        stage_rngs = jax.random.split(r_stage, n_stages)
        stacked = jax.vmap(
            lambda r: stage_mod.init(r, mb, False)["params"]
        )(stage_rngs)
        head_p = head_mod.init(r_head, mb)["params"]
        return {"embed": embed_p, "stages": stacked, "head": head_p}

    def apply_fn(params, tokens, training=False, rngs=None):
        x = embed_mod.apply({"params": params["embed"]}, tokens)
        x_micro = microbatch(x, num_microbatches)
        dropout_rng = (rngs or {}).get("dropout")
        need_rng = bool(cfg.dropout) and training
        if need_rng and dropout_rng is None:
            raise ValueError(
                "training with cfg.dropout > 0 requires "
                "rngs={'dropout': key} (per-stage/tick keys are derived "
                "inside the pipeline)"
            )
        if need_rng:
            def stage_fn(p, xm, r):
                return stage_mod.apply(
                    {"params": p}, xm, training, rngs={"dropout": r}
                )
        else:
            def stage_fn(p, xm):
                return stage_mod.apply({"params": p}, xm, training)

        pipe = make_pipeline(
            stage_fn, mesh, axis_name=axis_name, batch_axis=batch_axis,
            remat=cfg.remat, remat_policy=cfg.remat_policy,
        )
        y = unmicrobatch(
            pipe(params["stages"], x_micro, dropout_rng)
            if need_rng
            else pipe(params["stages"], x_micro)
        )
        return head_mod.apply({"params": params["head"]}, y)

    return init_fn, apply_fn


def make_lm_sequential(cfg, total_rows):
    """Schedule-free forward over the pipelined LM param tree: embed ->
    lax.scan over the stacked stage rows -> head. Mathematically identical
    to the monolithic TransformerLM (the stacked rows ARE the layer stack,
    in order: gpipe/1f1b stack stages 0..N-1 and the interleaved build's
    public tree is chunk-ordered, i.e. also sequential). Needs no mesh and
    no stage axis, so it serves as (a) the evaluation/prediction forward —
    eval tasks run on ONE worker's local devices — and (b) the trainer's
    pure-DP fallback when an elastic world can't host the stage axis,
    keeping the param tree (and therefore checkpoints, broadcasts, and
    optimizer state) intact across the degradation.

    total_rows: leading dim of params["stages"] (n_stages, or
    n_stages * virtual chunks for the interleaved build)."""
    import flax.linen as nn

    from elasticdl_tpu.models.transformer.transformer_lm import (
        Block,
        embed_input,
        head_output,
    )

    if cfg.n_layers % total_rows:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by {total_rows} "
            f"stage rows"
        )
    layers_per_row = cfg.n_layers // total_rows

    class EmbedIn(nn.Module):
        @nn.compact
        def __call__(self, tokens):
            return embed_input(cfg, tokens)

    class Stage(nn.Module):
        @nn.compact
        def __call__(self, x, training=False):
            for _ in range(layers_per_row):
                x = Block(cfg)(x, training)
            return x

    class HeadOut(nn.Module):
        @nn.compact
        def __call__(self, x):
            return head_output(cfg, x)

    embed_mod, stage_mod, head_mod = EmbedIn(), Stage(), HeadOut()

    def apply_fn(params, tokens, training=False, rngs=None):
        x = embed_mod.apply({"params": params["embed"]}, tokens)
        dropout_rng = (rngs or {}).get("dropout")
        if bool(cfg.dropout) and training and dropout_rng is not None:
            keys = jax.random.split(dropout_rng, total_rows)

            def body(h, xs):
                row_p, key = xs
                return (
                    stage_mod.apply(
                        {"params": row_p}, h, training,
                        rngs={"dropout": key},
                    ),
                    None,
                )

            x, _ = jax.lax.scan(body, x, (params["stages"], keys))
        else:

            def body(h, row_p):
                return stage_mod.apply({"params": row_p}, h, training), None

            x, _ = jax.lax.scan(body, x, params["stages"])
        return head_mod.apply({"params": params["head"]}, x)

    return apply_fn


# ---------- 1F1B schedule ----------


def vocab_parallel_head_loss(cfg, head_ln, v_loc, axis_name, head_params,
                             y, labels_m, shard):
    """Vocab-parallel CE for one microbatch, shared by the 1F1B and
    interleaved-1F1B schedules: each shard computes its [v_loc] logit
    slice; pmax/psum over `axis_name` assemble the full log-sum-exp and
    label logit. Returns the mean CE over this shard's tokens.

    Gradient conventions the CALLER must match: under shard_map with
    check_vma=False the internal psums TRANSPOSE TO PSUM, so each
    device's vjp cotangents (d_head, dy) come out axis-size x their true
    share — combine with psum(...)/n. The max is stop_gradient'd BEFORE
    the pmax (pmax has no differentiation rule; the max only stabilizes
    the exp)."""
    z = head_ln.apply(
        {"params": head_params["LayerNorm_0"]}, y
    ).astype(jnp.float32)
    kernel = head_params["lm_head"]["kernel"].astype(jnp.float32)
    bias = head_params["lm_head"]["bias"].astype(jnp.float32)
    k_loc = jax.lax.dynamic_slice_in_dim(
        kernel, shard * v_loc, v_loc, axis=1
    )
    b_loc = jax.lax.dynamic_slice_in_dim(bias, shard * v_loc, v_loc, 0)
    logits = z @ k_loc + b_loc  # [mb, S, v_loc]
    m_loc = jax.lax.stop_gradient(jnp.max(logits, axis=-1))
    m_glob = jax.lax.pmax(m_loc, axis_name)
    sumexp = jnp.sum(jnp.exp(logits - m_glob[..., None]), axis=-1)
    lse = m_glob + jnp.log(jax.lax.psum(sumexp, axis_name))
    rel = labels_m.astype(jnp.int32) - shard * v_loc
    in_range = (rel >= 0) & (rel < v_loc)
    gathered = jnp.take_along_axis(
        logits, jnp.clip(rel, 0, v_loc - 1)[..., None], axis=-1
    )[..., 0]
    label_logit = jax.lax.psum(
        jnp.where(in_range, gathered, 0.0), axis_name
    )
    return jnp.mean(lse - label_logit)


def make_lm_pipeline_1f1b(cfg, mesh, n_stages, num_microbatches,
                          axis_name="stage", batch_axis=None):
    """1F1B-scheduled pipelined LM training: returns (init_fn,
    loss_and_grads_fn) where loss_and_grads_fn(params, tokens, labels,
    rng=None) -> (loss, grads) with grads shaped like params.

    Same param tree as make_lm_pipeline (init functions are
    interchangeable); different schedule and memory shape:

    - GPipe above banks the inter-stage activation of EVERY tick for scan
      autodiff: O(M) residency per device. Here backward for microbatch m
      starts as soon as its forward leaves the last stage (classic 1F1B:
      bwd of m at stage i runs at tick m + 2(N-1) - i), so a stage only
      stashes the inputs of its in-flight microbatches — a 2N-deep ring,
      O(stages) residency independent of M. The stage backward re-runs its
      forward inside jax.vjp (the remat recipe), so compute matches
      remat'd GPipe.
    - SPMD uniformity: shard_map compiles ONE program for all stages, so
      per-stage special-casing must be masked, not branched. The LM head
      would be a masked hot spot (only the last stage needs it), so it is
      VOCAB-PARALLEL over the stage axis instead: every tick, every stage
      computes its V/N logit slice of the freshly-finished microbatch and
      the cross-entropy combines with pmax/psum — total head FLOPs equal
      the unsharded head, spread evenly, nothing masked out. Embedding is
      folded into stage 0's forward (a gather; uniform-cost tax is
      negligible) so its gradient rides the normal stage backward.
    - The loss (not logits) is the output: 1F1B exists to avoid
      materializing per-microbatch activations, so the training contract
      is loss_and_grads, not apply.

    Schedule: T = M + 2(N-1) ticks; stage i runs fwd of microbatch m at
    tick m + i and bwd of m at tick m + 2(N-1) - i; activations hop
    forward and gradients hop backward on neighbor-only ppermute rings.
    """
    import flax.linen as nn
    from elasticdl_tpu.common.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.models.transformer.transformer_lm import (
        Block,
        embed_input,
    )

    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by {n_stages} stages"
        )
    if cfg.vocab % n_stages:
        raise ValueError(
            f"vocab {cfg.vocab} not divisible by {n_stages} stages "
            f"(the 1F1B head is vocab-parallel over the stage axis)"
        )
    layers_per_stage = cfg.n_layers // n_stages
    v_loc = cfg.vocab // n_stages
    act_dtype = jnp.dtype(cfg.activation_dtype)

    class EmbedIn(nn.Module):
        @nn.compact
        def __call__(self, tokens):
            return embed_input(cfg, tokens)

    class Stage(nn.Module):
        @nn.compact
        def __call__(self, x, training=False):
            for _ in range(layers_per_stage):
                x = Block(cfg)(x, training)
            return x

    embed_mod, stage_mod = EmbedIn(), Stage()
    # Head params match make_lm_pipeline's HeadOut: LayerNorm_0 + lm_head.
    head_ln = nn.LayerNorm(dtype=act_dtype, name=None)

    def init_fn(rng, sample_tokens):
        # Delegate to the GPipe factory: identical param tree by
        # construction, so checkpoints/optimizer state transfer between
        # schedules.
        gpipe_init, _ = make_lm_pipeline(
            cfg, mesh, n_stages, num_microbatches,
            axis_name=axis_name, batch_axis=batch_axis,
        )
        return gpipe_init(rng, sample_tokens)

    def _head_loss(head_params, y, labels_m, stage):
        return vocab_parallel_head_loss(
            cfg, head_ln, v_loc, axis_name, head_params, y, labels_m,
            stage,
        )

    def _stage_forward(stage_params, embed_params, x_in, tokens_m, stage,
                       training, rng_m):
        """Uniform per-tick stage program: stage 0 embeds its tokens, the
        rest consume the neighbor activation; then this stage's blocks.
        The jnp.where routes gradients correctly (the unselected branch
        gets a zero cotangent), so one vjp of this function yields
        d_stage, d_embed (nonzero only on stage 0) and dx."""
        emb = embed_mod.apply({"params": embed_params}, tokens_m)
        h = jnp.where(stage == 0, emb, x_in)
        if rng_m is None:
            return stage_mod.apply({"params": stage_params}, h, training)
        return stage_mod.apply(
            {"params": stage_params}, h, training,
            rngs={"dropout": rng_m},
        )

    def _pipeline_1f1b(stages_p, embed_p, head_p, tokens_mb, labels_mb,
                       rng):
        n = n_stages
        stage = jax.lax.axis_index(axis_name)
        params_local = jax.tree_util.tree_map(lambda a: a[0], stages_p)
        num_micro = tokens_mb.shape[0]
        ticks = num_micro + 2 * (n - 1)
        stash_depth = 2 * n
        mb, s = tokens_mb.shape[1], tokens_mb.shape[2]
        act_shape = (mb, s, cfg.d_model)
        perm_fwd = [(i, (i + 1) % n) for i in range(n)]
        perm_bwd = [(i, (i - 1) % n) for i in range(n)]
        training = True

        def rng_for(m):
            if rng is None:
                return None
            r = jax.random.fold_in(jax.random.fold_in(rng, stage), m)
            if batch_axis is not None:
                r = jax.random.fold_in(
                    r, jax.lax.axis_index(batch_axis)
                )
            return r

        zero_grads = (
            jax.tree_util.tree_map(jnp.zeros_like, params_local),
            jax.tree_util.tree_map(jnp.zeros_like, embed_p),
            jax.tree_util.tree_map(jnp.zeros_like, head_p),
        )

        def tick(carry, t):
            fwd_msg, bwd_msg, stash, grads, loss_sum = carry
            d_stage_acc, d_embed_acc, d_head_acc = grads

            # ---- forward slot: microbatch m_f = t - stage ----
            m_f = t - stage
            fwd_valid = jnp.logical_and(m_f >= 0, m_f < num_micro)
            m_f_safe = jnp.clip(m_f, 0, num_micro - 1)
            tokens_f = jax.lax.dynamic_index_in_dim(
                tokens_mb, m_f_safe, 0, keepdims=False
            )
            y = _stage_forward(
                params_local, embed_p, fwd_msg, tokens_f, stage,
                training, rng_for(m_f_safe),
            )
            # Stash the consumed input for this microbatch's backward.
            slot = m_f_safe % stash_depth
            cur = jax.lax.dynamic_index_in_dim(
                stash, slot, 0, keepdims=False
            )
            stash = jax.lax.dynamic_update_index_in_dim(
                stash, jnp.where(fwd_valid, fwd_msg, cur), slot, 0
            )

            # ---- head slot: the microbatch that just left the last
            # stage (m_h = t - (N-1)), vocab-parallel on every stage ----
            m_h = t - (n - 1)
            head_valid = jnp.logical_and(m_h >= 0, m_h < num_micro)
            m_h_safe = jnp.clip(m_h, 0, num_micro - 1)
            y_last = jax.lax.psum(
                jnp.where(stage == n - 1, y, 0.0), axis_name
            )
            labels_h = jax.lax.dynamic_index_in_dim(
                labels_mb, m_h_safe, 0, keepdims=False
            )
            loss_m, head_vjp = jax.vjp(
                lambda hp, yy: _head_loss(hp, yy, labels_h, stage),
                head_p,
                y_last,
            )
            d_head_c, dy = head_vjp(jnp.float32(1.0 / num_micro))
            # Combining the per-slice vjp partials: under shard_map with
            # check_vma=False the psums inside _head_loss TRANSPOSE TO
            # PSUM, so each device's raw cotangent is already n x its true
            # share; psum-then-divide yields the exact total (verified
            # numerically against GPipe autodiff — a plain psum here reads
            # n x high on every leaf).
            dy = jax.lax.psum(dy, axis_name) / n
            loss_sum = loss_sum + jnp.where(
                head_valid, loss_m / num_micro, 0.0
            )
            d_head_acc = jax.tree_util.tree_map(
                lambda acc, g: acc + jnp.where(head_valid, g, 0.0),
                d_head_acc,
                d_head_c,
            )

            # ---- backward slot: microbatch m_b = t - 2(N-1) + stage ----
            m_b = t - 2 * (n - 1) + stage
            bwd_valid = jnp.logical_and(m_b >= 0, m_b < num_micro)
            m_b_safe = jnp.clip(m_b, 0, num_micro - 1)
            x_b = jax.lax.dynamic_index_in_dim(
                stash, m_b_safe % stash_depth, 0, keepdims=False
            )
            tokens_b = jax.lax.dynamic_index_in_dim(
                tokens_mb, m_b_safe, 0, keepdims=False
            )
            # The last stage's backward seed is the dy it just computed
            # (its bwd tick for m coincides with m's head tick); other
            # stages consume the gradient hopped back from their
            # successor.
            g = jnp.where(
                stage == n - 1, dy.astype(act_dtype), bwd_msg
            )
            _, stage_vjp = jax.vjp(
                lambda sp, ep, xx: _stage_forward(
                    sp, ep, xx, tokens_b, stage, training,
                    rng_for(m_b_safe),
                ),
                params_local,
                embed_p,
                x_b,
            )
            d_stage_c, d_embed_c, dx = stage_vjp(g)
            d_stage_acc = jax.tree_util.tree_map(
                lambda acc, gg: acc + jnp.where(bwd_valid, gg, 0.0),
                d_stage_acc,
                d_stage_c,
            )
            d_embed_acc = jax.tree_util.tree_map(
                lambda acc, gg: acc + jnp.where(bwd_valid, gg, 0.0),
                d_embed_acc,
                d_embed_c,
            )

            # ---- neighbor hops ----
            fwd_msg = jax.lax.ppermute(
                jnp.where(fwd_valid, y, 0.0), axis_name, perm_fwd
            )
            bwd_msg = jax.lax.ppermute(
                jnp.where(bwd_valid, dx, 0.0), axis_name, perm_bwd
            )
            return (
                fwd_msg,
                bwd_msg,
                stash,
                (d_stage_acc, d_embed_acc, d_head_acc),
                loss_sum,
            ), None

        carry0 = (
            jnp.zeros(act_shape, act_dtype),
            jnp.zeros(act_shape, act_dtype),
            jnp.zeros((stash_depth, *act_shape), act_dtype),
            zero_grads,
            jnp.float32(0.0),
        )
        (_, _, _, grads, loss_sum), _ = jax.lax.scan(
            tick, carry0, jnp.arange(ticks)
        )
        d_stage_acc, d_embed_acc, d_head_acc = grads
        # Each device accumulated only its own masked share of the
        # replicated embed/head grads and loss: combine over the stage
        # axis (loss was computed replicated per tick, so mean it).
        d_embed = jax.tree_util.tree_map(
            lambda gg: jax.lax.psum(gg, axis_name), d_embed_acc
        )
        # Head partials carry the same n x transpose factor as dy (see
        # the head slot); embed partials do not (stage_forward has no
        # internal collectives, and only stage 0's contribution is
        # nonzero).
        d_head = jax.tree_util.tree_map(
            lambda gg: jax.lax.psum(gg, axis_name) / n, d_head_acc
        )
        loss = jax.lax.pmean(loss_sum, axis_name)
        if batch_axis is not None:
            # Data-parallel composition: every grad (and the loss) is the
            # mean over batch shards.
            d_embed, d_head, d_stage_acc, loss = jax.tree_util.tree_map(
                lambda gg: jax.lax.pmean(gg, batch_axis),
                (d_embed, d_head, d_stage_acc, loss),
            )
        # Restore the stacked leading stage dim for the out_spec.
        d_stages = jax.tree_util.tree_map(
            lambda gg: gg[None], d_stage_acc
        )
        return loss, {
            "embed": d_embed,
            "stages": d_stages,
            "head": d_head,
        }

    def loss_and_grads_fn(params, tokens, labels, rng=None):
        if bool(cfg.dropout) and rng is None:
            raise ValueError(
                "training with cfg.dropout > 0 requires an explicit rng "
                "(per-stage/microbatch keys are derived inside the "
                "pipeline)"
            )
        tokens_mb = microbatch(
            jnp.asarray(tokens, jnp.int32), num_microbatches
        )
        labels_mb = microbatch(
            jnp.asarray(labels, jnp.int32), num_microbatches
        )
        stage_specs = jax.tree_util.tree_map(
            lambda _: P(axis_name), params["stages"]
        )
        repl_specs_e = jax.tree_util.tree_map(
            lambda _: P(), params["embed"]
        )
        repl_specs_h = jax.tree_util.tree_map(
            lambda _: P(), params["head"]
        )
        x_spec = P(None, batch_axis)
        in_specs = (
            stage_specs, repl_specs_e, repl_specs_h, x_spec, x_spec,
        )
        out_specs = (
            P(),
            {
                "embed": repl_specs_e,
                "stages": stage_specs,
                "head": repl_specs_h,
            },
        )
        if rng is None:
            return shard_map(
                lambda sp, ep, hp, tm, lm: _pipeline_1f1b(
                    sp, ep, hp, tm, lm, None
                ),
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=False,
            )(
                params["stages"], params["embed"], params["head"],
                tokens_mb, labels_mb,
            )
        return shard_map(
            _pipeline_1f1b,
            mesh=mesh,
            in_specs=in_specs + (P(),),
            out_specs=out_specs,
            check_vma=False,
        )(
            params["stages"], params["embed"], params["head"],
            tokens_mb, labels_mb, rng,
        )

    return init_fn, loss_and_grads_fn


def lm_pipeline_param_specs(params, axis_name="stage"):
    """PartitionSpecs for make_lm_pipeline params: stages sharded over the
    pipeline axis on their stacked leading dim, embed/head replicated —
    feed through NamedSharding for jit in_shardings."""
    from jax.sharding import PartitionSpec as P

    return {
        "embed": jax.tree_util.tree_map(lambda _: P(), params["embed"]),
        "stages": jax.tree_util.tree_map(
            lambda _: P(axis_name), params["stages"]
        ),
        "head": jax.tree_util.tree_map(lambda _: P(), params["head"]),
    }
