"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

Capability extension beyond the reference (which is DP-only; SURVEY.md §2.10
records no TP/PP/SP/EP anywhere upstream). TPU-first design: per-stage
parameters are STACKED along a leading axis and sharded over the "stage"
mesh axis, so each device owns exactly one stage's weights. The whole
pipeline — fills, steady state, and drain — is ONE `lax.scan` over
`num_microbatches + num_stages - 1` ticks inside `shard_map`: at every tick
each device runs its stage on the activation received from its neighbor on
the previous tick (`lax.ppermute` ring shift), stage 0 feeding fresh
microbatches and the last stage banking finished ones. Differentiating
through the scan + ppermute yields the mirrored backward schedule
automatically, and XLA compiles the full fwd+bwd pipeline (bubble included)
into a single SPMD program whose stage hops ride ICI.

Why this shape and not a Python loop of per-stage jits: under jit the scan
is traced once with static shapes, collectives are neighbor-only
ppermutes (no host round-trips between microbatches), and the bubble cost
is the schedule's only overhead — (N-1)/(M+N-1) of ticks idle per device,
amortized by raising M.

Memory: scan autodiff saves each tick's activations; with `remat=True` the
stage body is wrapped in `jax.checkpoint`, storing only the inter-stage
activations (O(M) per device) and recomputing block internals — the same
recipe the flagship LM uses for long context.

Composes with data parallelism: on a ("data", "stage") mesh the microbatch
batch dim is sharded over "data" while params shard over "stage"; every
collective here names only the stage axis.
"""

import functools

import jax
import jax.numpy as jnp


def pipeline_apply(stage_fn, stage_params, x_micro, axis_name="stage",
                   rng=None, batch_axis=None):
    """Run microbatches through the pipeline. Call INSIDE shard_map.

    stage_fn: (params_for_one_stage, x_microbatch) -> y_microbatch, with
      output shaped like the input (the inter-stage activation contract).
      When `rng` is given, called as (params, x, tick_rng) instead, with
      tick_rng distinct per (stage, tick, data-shard) — fold_in of the
      stage index, tick counter, and (when `batch_axis` names a DP mesh
      axis) the data-shard index — so stochastic layers (dropout) draw
      independent bits per stage, microbatch, and batch shard.
    stage_params: pytree whose leaves have a leading stage axis; sharded
      over `axis_name`, so inside shard_map the local leading dim is 1.
    x_micro: [M, mb, ...] microbatched input, replicated over `axis_name`.
    Returns [M, mb, ...] outputs, replicated over `axis_name` (the last
    stage's results are broadcast with a masked psum).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    params_local = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    num_micro = x_micro.shape[0]
    ticks = num_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 consumes fresh microbatch t during the fill; other
        # stages consume what arrived from their neighbor last tick.
        fresh = jax.lax.dynamic_index_in_dim(
            x_micro, jnp.minimum(t, num_micro - 1), 0, keepdims=False
        )
        inp = jnp.where(stage == 0, fresh, state)
        if rng is None:
            out = stage_fn(params_local, inp)
        else:
            tick_rng = jax.random.fold_in(
                jax.random.fold_in(rng, stage), t
            )
            if batch_axis is not None:
                # rng enters shard_map replicated; without this fold the
                # same dropout mask would repeat across every DP shard.
                tick_rng = jax.random.fold_in(
                    tick_rng, jax.lax.axis_index(batch_axis)
                )
            out = stage_fn(params_local, inp, tick_rng)
        # The last stage banks microbatch t-(N-1) once the pipe is full.
        out_idx = t - (n_stages - 1)
        bank = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
        safe = jnp.clip(out_idx, 0, num_micro - 1)
        cur = jax.lax.dynamic_index_in_dim(outputs, safe, 0,
                                           keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(bank, out, cur), safe, 0
        )
        state = jax.lax.ppermute(out, axis_name, perm)
        return (state, outputs), None

    state0 = jnp.zeros_like(x_micro[0])
    outputs0 = jnp.zeros_like(x_micro)
    (_, outputs), _ = jax.lax.scan(
        tick, (state0, outputs0), jnp.arange(ticks)
    )
    # Broadcast the last stage's banked outputs to every stage so the
    # result is replicated over the pipeline axis.
    return jax.lax.psum(
        jnp.where(stage == n_stages - 1, outputs, 0), axis_name
    )


def make_pipeline(stage_fn, mesh, axis_name="stage", batch_axis=None,
                  remat=False, remat_policy=None):
    """shard_map-wrapped pipeline: takes GLOBAL (stage_params, x_micro)
    with params stacked [n_stages, ...] (sharded over `axis_name`) and
    x_micro [M, mb, ...] (optionally sharded over `batch_axis` on mb for
    DP x PP meshes); returns [M, mb, ...] outputs with x's sharding."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    if remat:
        kwargs = {}
        if remat_policy:
            kwargs["policy"] = getattr(
                jax.checkpoint_policies, remat_policy
            )
        stage_fn = jax.checkpoint(stage_fn, **kwargs)
    x_spec = P(None, batch_axis)

    def _validate(stage_params, x_micro):
        # Fail with actionable messages instead of shard_map internals.
        n_stages = mesh.shape[axis_name]
        lead = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
        if lead != n_stages:
            raise ValueError(
                f"stage_params leading dim {lead} != mesh axis "
                f"{axis_name!r} size {n_stages}"
            )
        if batch_axis is not None:
            dp = mesh.shape[batch_axis]
            mb = x_micro.shape[1]
            if mb % dp:
                raise ValueError(
                    f"microbatch size {mb} not divisible by "
                    f"{batch_axis!r} axis size {dp}; adjust the batch "
                    f"size or num_microbatches"
                )

    def wrapper(stage_params, x_micro, rng=None):
        _validate(stage_params, x_micro)
        p_specs = jax.tree_util.tree_map(
            lambda _: P(axis_name), stage_params
        )
        if rng is None:
            def run(stage_params, x_micro):
                return pipeline_apply(
                    stage_fn, stage_params, x_micro, axis_name=axis_name
                )

            return shard_map(
                run,
                mesh=mesh,
                in_specs=(p_specs, x_spec),
                out_specs=x_spec,
                check_vma=False,
            )(stage_params, x_micro)

        def run_rng(stage_params, x_micro, rng):
            return pipeline_apply(
                stage_fn, stage_params, x_micro, axis_name=axis_name,
                rng=rng, batch_axis=batch_axis,
            )

        return shard_map(
            run_rng,
            mesh=mesh,
            in_specs=(p_specs, x_spec, P()),
            out_specs=x_spec,
            check_vma=False,
        )(stage_params, x_micro, rng)

    return wrapper


def microbatch(x, num_microbatches):
    """[B, ...] -> [M, B//M, ...]; B must divide evenly."""
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} not divisible by {num_microbatches} microbatches"
        )
    return x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])


def unmicrobatch(y):
    """[M, mb, ...] -> [M*mb, ...]."""
    return y.reshape(y.shape[0] * y.shape[1], *y.shape[2:])


def stack_stage_params(per_stage):
    """List of per-stage param pytrees -> one pytree with a leading stage
    axis (what pipeline_apply expects, sharded P('stage', ...))."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage
    )


# ---------- pipelined transformer LM (flagship integration) ----------


def make_lm_pipeline(cfg, mesh, n_stages, num_microbatches,
                     axis_name="stage", batch_axis=None):
    """A pipelined build of the flagship transformer LM: embedding and LM
    head run replicated over the stage axis (they are a small fraction of
    the FLOPs), the Block stack is split into `n_stages` equal stages and
    pipelined. Returns (init_fn, apply_fn):

      init_fn(rng, sample_tokens) -> params
          {"embed": ..., "stages": stacked [n_stages, ...], "head": ...}
      apply_fn(params, tokens, training=False) -> [B, S, vocab] logits
    """
    import flax.linen as nn

    from elasticdl_tpu.models.transformer.transformer_lm import (
        Block,
        embed_input,
        head_output,
    )

    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by {n_stages} stages"
        )
    layers_per_stage = cfg.n_layers // n_stages

    # Thin module shells around the SAME embed/head implementations the
    # monolithic TransformerLM uses (transformer_lm.embed_input /
    # head_output) — the only pipeline-specific structure is the stage
    # grouping of Blocks.
    class EmbedIn(nn.Module):
        @nn.compact
        def __call__(self, tokens):
            return embed_input(cfg, tokens)

    class Stage(nn.Module):
        @nn.compact
        def __call__(self, x, training=False):
            for _ in range(layers_per_stage):
                x = Block(cfg)(x, training)
            return x

    class HeadOut(nn.Module):
        @nn.compact
        def __call__(self, x):
            return head_output(cfg, x)

    embed_mod, stage_mod, head_mod = EmbedIn(), Stage(), HeadOut()

    def init_fn(rng, sample_tokens):
        r_embed, r_stage, r_head = jax.random.split(rng, 3)
        embed_p = embed_mod.init(r_embed, sample_tokens)["params"]
        sample_x = embed_mod.apply({"params": embed_p}, sample_tokens)
        mb = sample_x[: max(1, sample_x.shape[0] // num_microbatches)]
        stage_rngs = jax.random.split(r_stage, n_stages)
        stacked = jax.vmap(
            lambda r: stage_mod.init(r, mb, False)["params"]
        )(stage_rngs)
        head_p = head_mod.init(r_head, mb)["params"]
        return {"embed": embed_p, "stages": stacked, "head": head_p}

    def apply_fn(params, tokens, training=False, rngs=None):
        x = embed_mod.apply({"params": params["embed"]}, tokens)
        x_micro = microbatch(x, num_microbatches)
        dropout_rng = (rngs or {}).get("dropout")
        need_rng = bool(cfg.dropout) and training
        if need_rng and dropout_rng is None:
            raise ValueError(
                "training with cfg.dropout > 0 requires "
                "rngs={'dropout': key} (per-stage/tick keys are derived "
                "inside the pipeline)"
            )
        if need_rng:
            def stage_fn(p, xm, r):
                return stage_mod.apply(
                    {"params": p}, xm, training, rngs={"dropout": r}
                )
        else:
            def stage_fn(p, xm):
                return stage_mod.apply({"params": p}, xm, training)

        pipe = make_pipeline(
            stage_fn, mesh, axis_name=axis_name, batch_axis=batch_axis,
            remat=cfg.remat, remat_policy=cfg.remat_policy,
        )
        y = unmicrobatch(
            pipe(params["stages"], x_micro, dropout_rng)
            if need_rng
            else pipe(params["stages"], x_micro)
        )
        return head_mod.apply({"params": params["head"]}, y)

    return init_fn, apply_fn


def lm_pipeline_param_specs(params, axis_name="stage"):
    """PartitionSpecs for make_lm_pipeline params: stages sharded over the
    pipeline axis on their stacked leading dim, embed/head replicated —
    feed through NamedSharding for jit in_shardings."""
    from jax.sharding import PartitionSpec as P

    return {
        "embed": jax.tree_util.tree_map(lambda _: P(), params["embed"]),
        "stages": jax.tree_util.tree_map(
            lambda _: P(axis_name), params["stages"]
        ),
        "head": jax.tree_util.tree_map(lambda _: P(), params["head"]),
    }
