"""TPU-native parallelism: device meshes, sharded training steps, elastic
world management, and collective state broadcast.

This package replaces the reference's Horovod/Gloo allreduce stack
(/root/reference/elasticdl/python/worker/allreduce_trainer.py,
master/rendezvous_server.py) with jax.sharding meshes + XLA collectives over
ICI/DCN, and the Horovod broadcast with a gRPC parameter pull from the rank-0
worker.
"""

from elasticdl_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    data_sharding,
    replicated_sharding,
    pad_batch_to_multiple,
    shard_batch,
)
