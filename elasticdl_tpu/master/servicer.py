"""Master gRPC service.

Serves the worker-facing task protocol (reference
/root/reference/elasticdl/python/master/servicer.py:25-159): task pulls (with
WAIT when the queue is momentarily empty but the job is unfinished), task
results, evaluation metric reports, PS version reports (the evaluation
trigger), comm-rank queries for elastic AllReduce, and worker liveness.
"""

import os
import threading
import time

from elasticdl_tpu.chaos import injection
from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

logger = get_logger("master.servicer")


class MasterServicer:
    def __init__(
        self,
        task_dispatcher,
        evaluation_service=None,
        membership=None,
        worker_liveness_timeout=60.0,
        step_lease_manager=None,
    ):
        self._task_d = task_dispatcher
        self._evaluation_service = evaluation_service
        self._membership = membership
        self._step_leases = step_lease_manager
        # Same threshold the master watchdog uses, so alive_workers in the
        # job status can't contradict actual liveness decisions.
        self._worker_liveness_timeout = worker_liveness_timeout
        self._lock = threading.Lock()
        # worker_id -> last-RPC wall time, for the liveness watchdog
        # (reference servicer.py:93-94).
        self.worker_liveness = {}
        self.max_model_version = 0
        # Bound after construction (master.prepare) — the instance manager
        # and metrics endpoint exist only once the master is serving.
        self._instance_manager = None
        self._metrics_port = 0
        self._aggregator = None
        self._policy = None
        self._world_hints = None
        # Monotonic master incarnation (journal-recovered masters bump it)
        # stamped into JobStatusResponse so clients and workers can tell a
        # restart from a stall. 1 = first (or journal-less) life.
        self.master_incarnation = 1

    def bind_job_context(
        self,
        instance_manager=None,
        metrics_port=0,
        aggregator=None,
        policy=None,
        world_hints=None,
        master_incarnation=None,
    ):
        """Late-bind job-status sources created after this servicer."""
        self._instance_manager = instance_manager
        self._metrics_port = metrics_port
        self._aggregator = aggregator
        self._policy = policy
        self._world_hints = world_hints
        if master_incarnation is not None:
            self.master_incarnation = master_incarnation

    def _touch(self, worker_id):
        with self._lock:
            self.worker_liveness[worker_id] = time.time()

    def snapshot_liveness(self):
        """Copy of the liveness table (the watchdog iterates it while gRPC
        threads insert)."""
        with self._lock:
            return dict(self.worker_liveness)

    def forget_worker(self, worker_id):
        with self._lock:
            self.worker_liveness.pop(worker_id, None)

    def seed_liveness(self, worker_ids):
        """Grant recovery-time grace to workers that held journaled leases:
        a reappearing owner's next RPC refreshes this stamp and keeps its
        re-issued lease; one that never reappears ages out and the normal
        watchdog sweeps its tasks back to the queue."""
        now = time.time()
        with self._lock:
            for wid in worker_ids:
                self.worker_liveness.setdefault(wid, now)

    # ---------- rpc methods (names match rpc.MASTER_SERVICE) ----------

    def get_task(self, request, context):
        self._touch(request.worker_id)
        # Deterministic crash seam for the master-kill drill: a chaos
        # "kill" rule on this point SIGKILLs the master at the Nth
        # dispatch, BEFORE any lease is issued for this call.
        injection.inject_local("master.dispatch")
        if request.task_type == pb.EVALUATION:
            task_id, task = self._task_d.get_eval_task(request.worker_id)
        else:
            task_id, task = self._task_d.get(request.worker_id)
        if task is None:
            # Queue momentarily empty: tell the worker to WAIT unless the
            # whole job is done (then task_id stays -1 with default type).
            res = pb.Task(task_id=-1)
            if not self._task_d.finished():
                res.type = pb.WAIT
            return res
        # The dispatch is the root of the task's cross-process trace: an
        # instant event here plus the task_id in every downstream span
        # (the worker re-keys its context to this id) ties the chain
        # together in the merged trace.
        tracing.instant(
            "dispatch_task", task_id=task_id, worker=request.worker_id
        )
        return self._stamp_lease(task.to_proto(task_id))

    def _stamp_lease(self, task_pb):
        """Stamp the dispatcher's lease token into an outgoing Task proto
        so the worker can echo it with the result (exactly-once reporting
        across master restarts)."""
        task_pb.lease_token = self._task_d.lease_token(task_pb.task_id)
        return task_pb

    def get_task_batch(self, request, context):
        """Lease batching: up to max_tasks tasks in one RPC. An empty
        batch with finished=False is the WAIT analog."""
        self._touch(request.worker_id)
        injection.inject_local("master.dispatch")
        leased = self._task_d.get_batch(
            request.worker_id, max(1, request.max_tasks)
        )
        res = pb.TaskBatch()
        for task_id, task in leased:
            res.tasks.append(self._stamp_lease(task.to_proto(task_id)))
            tracing.instant(
                "dispatch_task", task_id=task_id, worker=request.worker_id
            )
        if not leased:
            res.finished = self._task_d.finished()
        return res

    def report_task_result(self, request, context):
        success = not request.err_message
        self._task_d.report(
            request.task_id, success, request.err_message,
            lease_token=request.lease_token,
        )
        return pb.Empty()

    def report_task_results(self, request, context):
        """Batched analog of report_task_result."""
        for entry in request.results:
            self._task_d.report(
                entry.task_id, not entry.err_message, entry.err_message,
                lease_token=entry.lease_token,
            )
        return pb.Empty()

    def get_world_hint(self, request, context):
        """The announced next worker world (policy scale events); workers
        poll this so the AOT speculator compiles the announced world."""
        self._touch(request.worker_id)
        if self._world_hints is None:
            return pb.WorldHintResponse()
        hint = self._world_hints.current()
        return pb.WorldHintResponse(
            hint_seq=hint["hint_seq"],
            target_world_size=hint["target_world_size"],
            reason=hint["reason"],
            age_seconds=hint["age_seconds"],
        )

    def report_evaluation_metrics(self, request, context):
        self._touch(request.worker_id)
        if self._evaluation_service is not None and request.model_outputs:
            decoded = [
                tensor_utils.tensor_pb_to_ndarray(t)
                for t in request.model_outputs
            ]
            # Single-output models report one tensor; multi-output models
            # report a list and their metrics receive the list.
            outputs = decoded[0] if len(decoded) == 1 else decoded
            labels = tensor_utils.tensor_pb_to_ndarray(request.labels)
            self._evaluation_service.report_evaluation_metrics(
                outputs, labels
            )
        return pb.Empty()

    def report_version(self, request, context):
        with self._lock:
            self.max_model_version = max(
                self.max_model_version, request.model_version
            )
        if self._evaluation_service is not None:
            self._evaluation_service.add_evaluation_task_if_needed(
                request.model_version
            )
        return pb.Empty()

    def get_comm_rank(self, request, context):
        if self._membership is None:
            return pb.GetCommRankResponse(rank_id=-1)
        (
            rank,
            world,
            group_id,
            coordinator,
            coordinator_port,
        ) = self._membership.get_comm_rank(request.worker_host)
        world_ready = False
        if request.ready_epoch_plus_one > 0:
            world_ready = self._membership.arrive(
                request.worker_host, request.ready_epoch_plus_one - 1
            )
        return pb.GetCommRankResponse(
            rank_id=rank,
            world_size=world,
            rendezvous_id=group_id,
            coordinator_addr=coordinator,
            rendezvous_port=coordinator_port,
            world_ready=world_ready,
        )

    def lease_steps(self, request, context):
        self._touch(request.worker_id)
        if self._step_leases is None:
            raise ValueError(
                "step leases are only served for the multi-host AllReduce "
                "strategy"
            )
        return self._step_leases.lease_steps(
            request.worker_id, request.worker_host, request.batch_size
        )

    def report_lease(self, request, context):
        self._touch(request.worker_id)
        if self._step_leases is not None:
            self._step_leases.report_lease(
                request.lease_id,
                request.rank,
                request.success,
                request.err_message,
            )
        return pb.Empty()

    def get_job_status(self, request, context):
        """Telemetry for `edl top` and other monitors (the in-job analog of
        the reference's pod-polling job monitor, k8s_job_monitor.py:94-207).
        Workers with an RPC inside the liveness timeout count as alive."""
        stats = self._task_d.stats()
        now = time.time()
        with self._lock:
            alive = sum(
                1
                for ts in self.worker_liveness.values()
                if now - ts < self._worker_liveness_timeout
            )
            last_seen_ago = {
                wid: now - ts
                for wid, ts in self.worker_liveness.items()
            }
            version = self.max_model_version
        res = pb.JobStatusResponse(
            todo_tasks=stats["todo"],
            doing_tasks=stats["doing"],
            epoch=stats["epoch"],
            num_epochs=stats["num_epochs"],
            model_version=version,
            alive_workers=alive,
            finished=self._task_d.finished(),
            job_failed=stats["job_failed"],
            records_done=stats["records_done"],
            tasks_recovered=stats.get("tasks_recovered", 0),
            tasks_abandoned=stats.get("tasks_abandoned", 0),
            metrics_port=self._metrics_port,
            master_incarnation=self.master_incarnation,
        )
        if self._instance_manager is not None:
            res.relaunches = self._instance_manager.total_relaunches()
        if self._membership is not None:
            res.membership_epoch = self._membership.group_id
        if self._aggregator is not None:
            # Straggler flags + alert count from the telemetry
            # aggregator, so `edl top` sees anomalies without scraping.
            res.stragglers.extend(self._aggregator.stragglers())
            res.alerts_fired = self._aggregator.alerts_fired()
        # Policy plane: applied actions, active blacklists, backup races.
        res.policy_blacklisted.extend(
            f"worker-{wid}" for wid in stats.get("blacklisted", [])
        )
        res.backup_tasks_inflight = stats.get("backups_inflight", 0)
        res.backup_wins = stats.get("backup_wins", 0)
        if self._policy is not None:
            res.policy_actions = self._policy.actions_total()
        for wid, age in last_seen_ago.items():
            res.worker_last_seen_ago[wid] = age
        for wid, n in stats["doing_by_worker"].items():
            res.worker_doing_tasks[wid] = n
        if (
            self._evaluation_service is not None
            and self._evaluation_service.completed_results
        ):
            eval_version, metrics = (
                self._evaluation_service.completed_results[-1]
            )
            res.last_eval_version = eval_version
            for name, value in metrics.items():
                res.last_eval_metrics[name] = float(value)
        return res

    def report_worker_liveness(self, request, context):
        self._touch(request.worker_id)
        if self._membership is not None and request.host:
            self._membership.register(request.worker_id, request.host)
        return pb.Empty()

    def report_telemetry(self, request, context):
        """Push-based telemetry: merge a batch of (delta-encoded) metric
        snapshots into the aggregator. Roles the aggregator cannot
        extend (sequence gap) come back in need_full, telling the
        reporter to resend a full snapshot. Without a bound aggregator
        every snapshot lands on need_full — the reporter keeps resending
        fulls, so binding late loses nothing but compression."""
        if self._aggregator is None:
            return pb.ReportTelemetryResponse(
                accepted=0,
                need_full=sorted(
                    {s.role for s in request.snapshots if s.role}
                ),
            )
        accepted, need_full = self._aggregator.ingest_push(
            request.snapshots, origin=request.origin
        )
        return pb.ReportTelemetryResponse(
            accepted=accepted, need_full=need_full
        )

    def start_profile(self, request, context):
        """Fan an on-demand device-profile capture out to every
        advertised endpoint (each role's /debug/profile HTTP endpoint),
        blocking until the captures return. Endpoint discovery rides the
        telemetry aggregator when one is bound, else the master's own
        obs dir."""
        import json

        from elasticdl_tpu.observability import profiling

        seconds = request.seconds or 2.0
        endpoints = self._profile_endpoints()
        if request.role_prefix:
            endpoints = [
                e
                for e in endpoints
                if e.get("role", "").startswith(request.role_prefix)
            ]
        results = profiling.fanout_profiles(endpoints, seconds)
        captured = sum(
            1 for r in results.values() if "error" not in r
        )
        logger.info(
            "Profile fan-out: %d/%d captures ok (%.1fs)",
            captured, len(results), seconds,
        )
        return pb.StartProfileResponse(
            captured=captured, results_json=json.dumps(results)
        )

    def _profile_endpoints(self):
        if self._aggregator is not None:
            return self._aggregator.discover_endpoints()
        from elasticdl_tpu import observability
        from elasticdl_tpu.observability.aggregator import (
            read_endpoints,
        )

        handle = observability.current_handle()
        if handle is None or not handle.obs_dir:
            return []
        return read_endpoints(os.path.join(handle.obs_dir, "endpoints"))
