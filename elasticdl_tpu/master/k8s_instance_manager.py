"""Kubernetes-backed elastic instance manager.

Reference counterpart: /root/reference/elasticdl/python/master/
k8s_instance_manager.py:53-439. Pod phase accounting from the watch stream;
relaunch on deletion or exit 137 that is not an OOM kill (= preemption,
k8s_instance_manager.py:327-348,391-404); task recovery + membership update
on worker failure. Import-gated via common/k8s_client; exercised only by
env-gated cluster tests (K8S_TESTS=true), mirroring the reference's gating
(k8s_instance_manager_test.py:25).
"""

import threading

from elasticdl_tpu.common import k8s_client
from elasticdl_tpu.common.constants import PodStatus
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.master.instance_manager import DEFAULT_MAX_RELAUNCHES
from elasticdl_tpu.observability import emit_event
from elasticdl_tpu.observability.metrics import default_registry

logger = get_logger("master.k8s_instance_manager")

# Same family the local-process manager registers; the registry returns
# the one shared metric for the name.
_POD_EVENTS = default_registry().counter(
    "edl_pod_events_total",
    "Instance lifecycle transitions seen by the master",
    labelnames=("kind", "event"),
)


class K8sInstanceManager:
    def __init__(
        self,
        namespace,
        job_name,
        image_name,
        command_for,
        num_workers=0,
        num_ps=0,
        task_dispatcher=None,
        membership=None,
        worker_resources=None,
        ps_resources=None,
        worker_priority=None,
        volumes=None,
        max_relaunches=DEFAULT_MAX_RELAUNCHES,
        envs=None,
        ps_service_port=50002,
    ):
        k8s_client.require_k8s()
        from elasticdl_tpu.common.k8s_resource import (
            parse_resource_spec,
            parse_volume_spec,
            parse_worker_priority,
        )

        self._command_for = command_for
        self._num_workers = num_workers
        self._num_ps = num_ps
        self._task_d = task_dispatcher
        self._membership = membership
        # Spec strings parse here ("cpu=4,memory=8Gi,tpu=4", "high=0.5",
        # "host_path=/data,mount_path=/data") so a bad spec fails the job
        # at submission, not at the first relaunch.
        self._worker_resources = parse_resource_spec(worker_resources)
        self._ps_resources = parse_resource_spec(ps_resources)
        self._worker_priority = parse_worker_priority(
            worker_priority, num_workers
        )
        self._volumes = parse_volume_spec(volumes)
        self._max_relaunches = max_relaunches
        self._envs = envs or {}
        self._ps_service_port = ps_service_port
        self._lock = threading.Lock()
        self._stopping = False
        self._statuses = {}  # (kind, id) -> PodStatus
        self._relaunches = {}  # (kind, id) -> count
        # (kind, id) -> current pod incarnation: a relaunch creates a NEW
        # pod name (-rN), so events from the dead predecessor (its
        # eventual DELETED, late MODIFIEDs) can be told apart from the
        # replacement's and ignored instead of cascading more relaunches.
        self._incarnations = {}
        self._client = k8s_client.Client(
            namespace, job_name, image_name, event_callback=self._event_cb
        )

    # ---------- lifecycle ----------

    def start_parameter_servers(self):
        for ps_id in range(self._num_ps):
            self._start("ps", ps_id)

    def start_workers(self):
        for worker_id in range(self._num_workers):
            self._start("worker", worker_id)

    def _start(self, kind, instance_id):
        resources = (
            self._ps_resources if kind == "ps" else self._worker_resources
        )
        # cpu/memory stay requests-only (a limit would turn a scheduling
        # hint into a throttle/OOM boundary); extended device resources
        # (nvidia.com/gpu, google.com/tpu) MUST appear in limits — the
        # device plugin API requires it.
        device_limits = {
            k: v for k, v in (resources or {}).items() if "/" in k
        }
        with self._lock:
            incarnation = self._incarnations.get((kind, instance_id), 0)
        self._client.create_pod(
            kind,
            instance_id,
            self._command_for(kind, instance_id),
            resource_requests=resources or None,
            resource_limits=device_limits or None,
            priority_class=(
                self._worker_priority.get(instance_id)
                if kind == "worker"
                else None
            ),
            envs=self._envs,
            volumes=self._volumes,
            incarnation=incarnation,
        )
        if kind == "ps":
            # Stable service name so a relaunched PS keeps its address and
            # workers re-seed it transparently (reference
            # k8s_instance_manager.py:399-404).
            with self._lock:
                first = (kind, instance_id) not in self._statuses
            if first:
                try:
                    self._client.create_service(
                        f"{self._client.job_name}-ps-{instance_id}",
                        self._ps_service_port,
                        kind,
                        instance_id,
                    )
                except Exception:
                    logger.warning(
                        "PS service creation failed (may already exist)",
                        exc_info=True,
                    )
        with self._lock:
            self._statuses[(kind, instance_id)] = PodStatus.PENDING
        _POD_EVENTS.labels(kind=kind, event="launch").inc()
        emit_event(
            "pod_launch",
            instance=f"{kind}-{instance_id}",
            incarnation=incarnation,
        )

    def stop(self):
        with self._lock:
            self._stopping = True
            keys = {
                (kind, instance_id): self._incarnations.get(
                    (kind, instance_id), 0
                )
                for (kind, instance_id) in self._statuses
            }
        self._client.stop()
        for (kind, instance_id), incarnation in keys.items():
            # Current incarnation plus any failed predecessors still
            # occupying their names.
            for inc in range(incarnation + 1):
                try:
                    self._client.delete_pod(kind, instance_id, inc)
                except Exception:
                    pass

    # ---------- watch-event state machine ----------

    def _event_cb(self, event):
        with self._lock:
            if self._stopping:
                # Teardown deletes are ours; treating them as preemptions
                # would resurrect the pods we just removed.
                return
        pod = event["object"]
        labels = pod.metadata.labels or {}
        kind = labels.get(k8s_client.ELASTICDL_REPLICA_TYPE_KEY)
        if kind not in ("worker", "ps"):
            return
        instance_id = int(
            labels.get(k8s_client.ELASTICDL_REPLICA_INDEX_KEY, -1)
        )
        with self._lock:
            incarnation = self._incarnations.get((kind, instance_id), 0)
        expected_name = self._client.pod_name(
            kind, instance_id, incarnation
        )
        pod_name = pod.metadata.name
        if pod_name is not None and pod_name != expected_name:
            # A dead predecessor's late event (e.g. its DELETED after we
            # already relaunched under a new name): not this replica's
            # current pod, so it must not drive the state machine.
            return
        phase = pod.status.phase
        deleted = event["type"] == "DELETED"
        with self._lock:
            prev = self._statuses.get((kind, instance_id))
        if phase == "Running" and prev != PodStatus.RUNNING:
            with self._lock:
                self._statuses[(kind, instance_id)] = PodStatus.RUNNING
            return
        if phase == "Succeeded":
            with self._lock:
                self._statuses[(kind, instance_id)] = PodStatus.SUCCEEDED
            _POD_EVENTS.labels(kind=kind, event="exit").inc()
            emit_event(
                "pod_exit", instance=f"{kind}-{instance_id}", exit_code=0
            )
            if kind == "worker" and self._membership is not None:
                self._membership.remove_worker(instance_id)
            return
        if deleted or phase == "Failed":
            relaunch = deleted or self._is_preempted(pod)
            self._on_failure(kind, instance_id, relaunch)

    @staticmethod
    def _is_preempted(pod):
        """Exit 137 that is NOT an OOMKill = preemption/eviction -> relaunch
        (the reference's policy, k8s_instance_manager.py:327-348)."""
        statuses = (pod.status.container_statuses or [])
        for cs in statuses:
            term = cs.state and cs.state.terminated
            if term and term.exit_code == 137 and term.reason != "OOMKilled":
                return True
        return False

    def _on_failure(self, kind, instance_id, relaunch):
        logger.warning(
            "%s %d failed (relaunch=%s)", kind, instance_id, relaunch
        )
        _POD_EVENTS.labels(kind=kind, event="exit").inc()
        emit_event(
            "pod_exit",
            instance=f"{kind}-{instance_id}",
            relaunchable=relaunch,
        )
        if kind == "worker":
            if self._task_d is not None:
                self._task_d.recover_tasks(instance_id)
            if self._membership is not None:
                self._membership.remove_worker(instance_id)
        with self._lock:
            count = self._relaunches.get((kind, instance_id), 0)
            can_relaunch = relaunch and count < self._max_relaunches
            if can_relaunch:
                self._relaunches[(kind, instance_id)] = count + 1
                # New incarnation = new pod name; the failed pod keeps
                # its name on the API server (re-creating it would 409).
                self._incarnations[(kind, instance_id)] = (
                    self._incarnations.get((kind, instance_id), 0) + 1
                )
                old_incarnation = (
                    self._incarnations[(kind, instance_id)] - 1
                )
            else:
                self._statuses[(kind, instance_id)] = PodStatus.FAILED
        if can_relaunch:
            _POD_EVENTS.labels(kind=kind, event="relaunch").inc()
            emit_event(
                "pod_relaunch",
                instance=f"{kind}-{instance_id}",
                attempt=count + 1,
            )
        else:
            _POD_EVENTS.labels(kind=kind, event="failed").inc()
            emit_event("pod_failed", instance=f"{kind}-{instance_id}")
        if can_relaunch:
            # Reap the failed predecessor (best-effort; it may already be
            # gone when the trigger was a deletion).
            try:
                self._client.delete_pod(
                    kind, instance_id, old_incarnation
                )
            except Exception:
                pass
            # PS keeps its id and service address so workers re-seed it
            # transparently (reference k8s_instance_manager.py:399-404).
            self._start(kind, instance_id)

    # ---------- status ----------

    def total_relaunches(self):
        """Cumulative relaunches across all instances (job-status RPC)."""
        with self._lock:
            return sum(self._relaunches.values())

    def all_workers_failed(self):
        with self._lock:
            workers = [
                s for (k, _), s in self._statuses.items() if k == "worker"
            ]
        return bool(workers) and all(s == PodStatus.FAILED for s in workers)

    def all_workers_done(self):
        with self._lock:
            workers = [
                s for (k, _), s in self._statuses.items() if k == "worker"
            ]
        return bool(workers) and all(
            s in (PodStatus.SUCCEEDED, PodStatus.FAILED) for s in workers
        )
