"""The master orchestrator: control plane of one elastic training job.

Reference counterpart: /root/reference/elasticdl/python/master/
master.py:97-509. Builds the task dispatcher from the dataset shards, serves
the Master gRPC service, spawns PS + worker instances through an instance
manager backend, and runs the poll loop: job completion, all-workers-failed
abort, the task-timeout watchdog (a task running > 3x the rolling mean
completion time gets its worker's tasks recovered and its membership entry
dropped, master.py:487-509), and the worker-liveness timeout
(servicer.py:93-94,131-148).
"""

import os
import sys
import time

from elasticdl_tpu import observability
from elasticdl_tpu.common import knobs, rpc
from elasticdl_tpu.common.args import build_arguments_from_parsed_result
from elasticdl_tpu.common.constants import DistributionStrategy
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.model_utils import get_model_spec
from elasticdl_tpu.data.reader import create_data_reader
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.instance_manager import (
    LocalProcessInstanceManager,
)
from elasticdl_tpu.master.membership import MembershipManager
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

logger = get_logger("master.master")

# Flag subsets relayed into spawned processes — each must stay within what
# the receiving parser (worker_parser / ps_parser) actually accepts.
_WORKER_RELAY_ARGS = [
    "job_name",
    "model_zoo",
    "model_def",
    "distribution_strategy",
    "minibatch_size",
    "get_model_steps",
    "ps_wire_dtype",
    "log_loss_steps",
    "seed",
    "model_parallel_size",
    "pipeline_stages",
    "pipeline_schedule",
    "pipeline_microbatches",
    "pipeline_virtual_stages",
    "context_parallel_size",
    "context_parallel_impl",
    "multi_host",
    "zero1",
    "quantized_grads",
    "training_data",
    "validation_data",
    "prediction_data",
    "records_per_task",
    "num_epochs",
    "prefetch_records",
    "profile_dir",
    "profile_start_step",
    "profile_steps",
]
_PS_RELAY_ARGS = [
    "job_name",
    "model_zoo",
    "model_def",
    "seed",
]


class Master:
    def __init__(self, args):
        self.args = args
        # The observability plane comes up FIRST so task creation, instance
        # launches, and every later lifecycle transition land in the event
        # log/registry. Spawned worker/PS processes find the same obs dir
        # (and the job identity) through the environment.
        obs_dir = getattr(args, "metrics_dir", "") or knobs.get_str(
            observability.OBS_DIR_ENV
        )
        if obs_dir:
            os.environ[observability.OBS_DIR_ENV] = obs_dir
        os.environ[observability.JOB_NAME_ENV] = args.job_name
        self.obs = observability.setup(
            role="master", job=args.job_name, obs_dir=obs_dir
        )
        # A fixed metrics port is the master's alone; local children must
        # bind ephemeral ports or they'd all collide on this host.
        os.environ.pop(observability.METRICS_PORT_ENV, None)
        if args.model_zoo:
            sys.path.insert(0, args.model_zoo)
        self.spec = get_model_spec(args.model_def)

        # --- data shards -> task dispatcher (reference master.py:61-94) ---
        reader_factory = self.spec.create_data_reader or create_data_reader
        training_shards = (
            reader_factory(args.training_data).create_shards()
            if args.training_data
            else {}
        )
        evaluation_shards = (
            reader_factory(args.validation_data).create_shards()
            if args.validation_data
            else {}
        )
        prediction_shards = (
            reader_factory(args.prediction_data).create_shards()
            if args.prediction_data
            else {}
        )
        self.task_d = TaskDispatcher(
            training_shards,
            evaluation_shards,
            prediction_shards,
            records_per_task=args.records_per_task,
            num_epochs=args.num_epochs,
            shuffle=args.shuffle_shards,
            seed=args.seed,
        )

        if args.checkpoint_dir_for_init and training_shards:
            # Restart-from-checkpoint: don't re-dispatch already-trained
            # records (reference master.py:185-201 restores the completed
            # step count from the checkpoint version).
            from elasticdl_tpu.ps.checkpoint import (
                latest_complete_version,
                read_total_records,
            )

            version = latest_complete_version(args.checkpoint_dir_for_init)
            if version:
                # The checkpoint carries the exact number of training
                # records consumed (version alone is ambiguous: a sync
                # window merges a variable number of pushes, and tasks end
                # in partial batches). Fall back to a version-based
                # estimate for pre-field checkpoints.
                records = read_total_records(
                    args.checkpoint_dir_for_init, version
                )
                if not records:
                    records = (
                        version
                        * (
                            1
                            if args.use_async
                            else max(args.grads_to_wait, 1)
                        )
                        * args.minibatch_size
                    )
                self.task_d.set_completed_records(records)

        self.metrics_service = None
        if getattr(args, "metrics_dir", ""):
            from elasticdl_tpu.master.metrics_service import MetricsService

            self.metrics_service = MetricsService(args.metrics_dir)

        self.evaluation_service = None
        if evaluation_shards:
            self.evaluation_service = EvaluationService(
                self.task_d,
                self.spec.build_metrics
                if self.spec.eval_metrics_fn
                else dict,
                eval_steps=args.evaluation_steps,
                on_results=(
                    self.metrics_service.on_evaluation_results
                    if self.metrics_service
                    else None
                ),
            )

        self.membership = (
            MembershipManager(coordinator_port=args.coordinator_port)
            if args.distribution_strategy == DistributionStrategy.ALLREDUCE
            else None
        )
        if args.output and training_shards:
            # Arm the final export task (reference: SavedModel export via a
            # train-end callback task, master/callbacks.py:38-66).
            self.task_d.enable_train_end_task()

        # --- survivable control plane (ELASTICDL_MASTER_JOURNAL_DIR) ---
        # Replay snapshot+WAL, restore the dispatcher/membership state,
        # bump the incarnation, and mirror every mutation from here on.
        # All init-time dispatcher setup above (task creation, checkpoint
        # fast-forward, train-end arming) happens BEFORE the attach, so
        # the WAL only ever holds post-start ops (prepare() snapshots the
        # merged state right before serving).
        from elasticdl_tpu.master.journal import open_master_journal

        self.journal = open_master_journal()
        self.master_incarnation = 1
        self._recovered_state = None
        self._recovered_leases = []
        if self.journal is not None:
            state = self.journal.load()
            # Every journaled master records its incarnation at startup,
            # so a nonzero replayed incarnation means a previous life.
            if state["incarnation"] > 0:
                self._recovered_state = state
                self.master_incarnation = state["incarnation"] + 1
                self.task_d.restore_state(state)
                self._recovered_leases = self.task_d.inflight_leases()
                if self.membership is not None:
                    self.membership.restore_state(state)
                logger.warning(
                    "Master journal replayed: incarnation %d, "
                    "records_done=%d, %d in-flight leases restored, "
                    "hint_seq=%d",
                    self.master_incarnation,
                    state["records_done"],
                    len(self._recovered_leases),
                    state["hint_seq"],
                )
            self.task_d.attach_journal(self.journal)
            self.journal.add_state_provider(self.task_d.export_state)
            if self.membership is not None:
                self.membership.attach_journal(self.journal)
                self.journal.add_state_provider(
                    self.membership.export_state
                )
            self.journal.add_state_provider(
                lambda: {"incarnation": self.master_incarnation}
            )
            self.journal.record({
                "op": "incarnation", "value": self.master_incarnation,
            })
        self.step_leases = None
        if self.membership is not None and getattr(
            args, "multi_host", False
        ):
            from elasticdl_tpu.master.step_lease import StepLeaseManager

            self.step_leases = StepLeaseManager(
                self.task_d, self.membership
            )
        self.servicer = MasterServicer(
            self.task_d,
            self.evaluation_service,
            self.membership,
            worker_liveness_timeout=args.worker_liveness_timeout_seconds,
            step_lease_manager=self.step_leases,
        )
        self._server = None
        self.port = None
        self.aggregator = None
        self.policy = None
        self.world_hints = None
        self.instance_manager = self._build_instance_manager(args)

    # ---------- instance manager wiring ----------

    def _build_instance_manager(self, args):
        if args.instance_backend == "none" or (
            args.num_workers == 0 and args.num_ps == 0
        ):
            return None
        if args.instance_backend == "local_process":
            return LocalProcessInstanceManager(
                self._command_for,
                num_workers=args.num_workers,
                num_ps=args.num_ps,
                task_dispatcher=self.task_d,
                membership=self.membership,
                max_relaunches=args.max_relaunches,
            )
        if args.instance_backend == "k8s":
            from elasticdl_tpu.master.k8s_instance_manager import (
                K8sInstanceManager,
            )

            envs = {observability.JOB_NAME_ENV: args.job_name}
            if knobs.is_set(observability.OBS_DIR_ENV):
                envs[observability.OBS_DIR_ENV] = knobs.raw(
                    observability.OBS_DIR_ENV
                )
            # Log identity/format follows the master into the pods so a
            # chaos run's JSON logs correlate across roles; the compile
            # cache dir follows so every pod of the job shares ONE
            # persistent cache (a relaunched pod rehydrates executables
            # its predecessor or peers already compiled).
            for var in (
                "ELASTICDL_LOG_LEVEL",
                "ELASTICDL_LOG_FORMAT",
                "ELASTICDL_COMPILE_CACHE_DIR",
            ):
                if knobs.is_set(var):
                    envs[var] = knobs.raw(var)
            return K8sInstanceManager(
                args.namespace,
                args.job_name,
                args.image_name,
                self._command_for,
                num_workers=args.num_workers,
                num_ps=args.num_ps,
                task_dispatcher=self.task_d,
                membership=self.membership,
                worker_resources=args.worker_resources,
                ps_resources=args.ps_resources,
                worker_priority=args.worker_pod_priority,
                volumes=args.volume,
                max_relaunches=args.max_relaunches,
                envs=envs,
            )
        raise ValueError(f"unknown backend {args.instance_backend!r}")

    def _master_addr(self):
        host = os.environ.get("MY_POD_IP", "127.0.0.1")
        return f"{host}:{self.port}"

    PS_SERVICE_PORT = 50002

    def _ps_addr(self, ps_id):
        # Local backend: PS picks port ps_base+ps_id on this host; k8s
        # backend: stable per-PS service names (created by the k8s instance
        # manager) on PS_SERVICE_PORT. master_port 0 means "bind any" for
        # the master itself and cannot seed PS ports — fall back to the
        # default base so PS ports stay valid.
        if self.args.instance_backend == "k8s":
            return (
                f"{self.args.job_name}-ps-{ps_id}:{self.PS_SERVICE_PORT}"
            )
        # With --master_port 0, derive from the ACTUALLY BOUND master port
        # (prepare() runs before any instance spawns) so two concurrent
        # jobs on one host don't collide on a fixed base.
        base = self.args.master_port or self.port or 50001
        return f"127.0.0.1:{base + 1 + ps_id}"

    def ps_addrs(self):
        return ",".join(
            self._ps_addr(i) for i in range(self.args.num_ps)
        )

    def _command_for(self, kind, instance_id):
        """argv for a spawned instance (reference master.py:424-476 builds
        worker/PS pod command lines the same way)."""
        relay = build_arguments_from_parsed_result(
            self.args,
            filter_args=(
                _WORKER_RELAY_ARGS if kind == "worker" else _PS_RELAY_ARGS
            ),
        )
        if kind == "worker":
            argv = [
                sys.executable,
                "-m",
                "elasticdl_tpu.worker.main",
                "--worker_id",
                str(instance_id),
                "--master_addr",
                self._master_addr(),
            ]
            if self.args.num_ps:
                argv += ["--ps_addrs", self.ps_addrs()]
            if self.args.training_data:
                if self.args.validation_data:
                    argv += ["--job_type", "training_with_evaluation"]
            elif self.args.validation_data:
                argv += ["--job_type", "evaluation_only"]
            elif self.args.prediction_data:
                argv += ["--job_type", "prediction_only"]
            for flag in ("output", "checkpoint_dir_for_init"):
                value = getattr(self.args, flag, "")
                if value:
                    argv += [f"--{flag}", str(value)]
            return argv + relay
        if kind == "ps":
            ps_port = int(self._ps_addr(instance_id).rsplit(":", 1)[1])
            argv = [
                sys.executable,
                "-m",
                "elasticdl_tpu.ps.main",
                "--ps_id",
                str(instance_id),
                "--num_ps",
                str(self.args.num_ps),
                "--port",
                str(ps_port),
                "--master_addr",
                self._master_addr(),
            ]
            for flag in (
                "checkpoint_dir",
                "checkpoint_steps",
                "keep_checkpoint_max",
                "checkpoint_dir_for_init",
                "grads_to_wait",
                "sync_version_tolerance",
                "sync_window_timeout",
            ):
                value = getattr(self.args, flag, None)
                # `is not None` so explicit numeric zeros (e.g.
                # --sync_window_timeout 0) still relay; empty-string
                # defaults for the path flags stay dropped.
                if value is not None and value != "":
                    argv += [f"--{flag}", str(value)]
            if not self.args.use_async:
                argv += ["--use_sync"]
            if self.args.lr_staleness_modulation:
                argv += ["--lr_staleness_modulation"]
            return argv + relay
        raise ValueError(kind)

    # ---------- lifecycle ----------

    def prepare(self):
        # Orphan-reaper beacon: while this file stays fresh the job's
        # process group is alive on purpose; once it goes stale,
        # tools/reap_orphans.py may SIGKILL the whole group.
        from elasticdl_tpu.common.heartbeat import HeartbeatWriter

        self._heartbeat = HeartbeatWriter(job=self.args.job_name).start()
        if self.obs.metrics_port:
            logger.info(
                "Prometheus metrics on :%d/metrics", self.obs.metrics_port
            )
        if self.obs.obs_dir:
            # Job-level telemetry: scrape every advertised per-role
            # endpoint, derive throughput/straggler/imbalance signals,
            # re-export them as edl_job_* gauges + /api/summary, and run
            # the alert rules. Needs the obs dir (endpoint discovery);
            # without one there is nothing to aggregate.
            from elasticdl_tpu.observability.aggregator import (
                TelemetryAggregator,
            )

            self.aggregator = TelemetryAggregator(
                self.obs.obs_dir, job=self.args.job_name
            ).start()
            if self.obs.exporter is not None:
                self.obs.exporter.summary_provider = (
                    self.aggregator.summary
                )
        from elasticdl_tpu.master.policy import (
            PolicyEngine,
            WorldHintBoard,
            policy_enabled,
        )

        self.world_hints = WorldHintBoard()
        if self.journal is not None:
            # hint_seq survives the restart: a board resuming from 0 would
            # make trainers silently ignore every post-restart hint.
            if self._recovered_state is not None:
                self.world_hints.restore_state(self._recovered_state)
            self.world_hints.attach_journal(self.journal)
            self.journal.add_state_provider(self.world_hints.export_state)
        if policy_enabled() and self.aggregator is not None:
            # The closed loop: aggregator signals -> rules -> actuators.
            # Scale decisions announce through the world-hint board first
            # so workers AOT-compile the announced world before it forms.
            self.policy = PolicyEngine(
                self.aggregator.summary,
                self.task_d,
                instance_manager=self.instance_manager,
                world_hints=self.world_hints,
            )
            if self.journal is not None:
                # Resume without re-firing already-applied decisions:
                # restored cooldowns keep them suppressed.
                if self._recovered_state is not None:
                    self.policy.restore_state(self._recovered_state)
                self.policy.attach_journal(self.journal)
                self.journal.add_state_provider(self.policy.export_state)
            self.policy.start()
            if self.obs.exporter is not None:
                self.obs.exporter.summary_provider = self._summary
        self.servicer.bind_job_context(
            instance_manager=self.instance_manager,
            metrics_port=self.obs.metrics_port,
            aggregator=self.aggregator,
            policy=self.policy,
            world_hints=self.world_hints,
            master_incarnation=self.master_incarnation,
        )
        if self.journal is not None:
            # Snapshot-on-start: fold the replayed (or fresh) state of
            # every provider into snapshot.json and truncate the WAL, so
            # replay time is bounded by post-start activity only.
            self.journal.compact()
        if self._recovered_state is not None:
            # Re-lease trail: owners that reappear within the liveness
            # window keep their restored leases (seed_liveness grants the
            # grace); the watchdog sweeps the rest back to the queue.
            owners = sorted({
                wid for _, wid, _ in self._recovered_leases
            })
            self.servicer.seed_liveness(owners)
            observability.emit_event(
                "master_recovered",
                incarnation=self.master_incarnation,
                records_done=self._recovered_state["records_done"],
                leases=len(self._recovered_leases),
                hint_seq=self._recovered_state["hint_seq"],
                membership_epoch=self._recovered_state[
                    "membership_epoch"
                ],
            )
            for tid, wid, task in self._recovered_leases:
                observability.emit_event(
                    "lease_reissued",
                    task_id=tid,
                    worker=wid,
                    shard=task.shard_name,
                    start=task.start,
                    end=task.end,
                )
        # Bind the port LAST: the first RPC any client can land must
        # already see the recovered world — bumped incarnation in
        # JobStatusResponse, restored hint board, seeded liveness. A
        # master that serves while still wiring recovery shows a
        # regressed hint_seq/incarnation window to riding workers.
        self._server, self.port = rpc.serve(
            self.servicer, rpc.MASTER_SERVICE, port=self.args.master_port
        )
        logger.info("Master serving on port %d", self.port)
        if self.instance_manager is not None:
            if self.args.num_ps:
                self.instance_manager.start_parameter_servers()
            self.instance_manager.start_workers()
        if (
            self.metrics_service is not None
            and self.args.instance_backend == "k8s"
        ):
            # In-cluster TensorBoard exposure (reference
            # k8s_tensorboard_client.py:22-66): a LoadBalancer service
            # pointing at this master pod; `edl tensorboard
            # --logdir <metrics_dir>` serves behind it.
            try:
                client = getattr(self.instance_manager, "_client", None)
                if client is not None:
                    client.create_tensorboard_service()
                    logger.info(
                        "Created TensorBoard LoadBalancer service "
                        "tensorboard-%s", self.args.job_name,
                    )
            except Exception:
                logger.warning(
                    "TensorBoard service creation failed", exc_info=True
                )

    def _summary(self):
        """Aggregator summary with the policy plane merged in, so
        /api/summary (and `edl dash`) shows decisions next to signals."""
        summary = self.aggregator.summary()
        if self.policy is not None:
            summary["policy"] = self.policy.summary()
        return summary

    def run(self, poll_seconds=None):
        """Poll until done/failed (reference master.py:238-263). Returns the
        process exit code."""
        poll = poll_seconds or min(
            5.0, self.args.task_timeout_check_seconds
        )
        last_watchdog = time.time()
        last_metrics = time.time()
        last_records = self.task_d.stats()["records_done"]
        # Brief linger before the server stops on ANY terminal path, so
        # monitors polling get_job_status can observe the terminal state
        # (finished OR failed) instead of an ambiguous UNAVAILABLE.
        def linger():
            time.sleep(
                getattr(self.args, "shutdown_linger_seconds", 2.0)
            )

        try:
            while True:
                if self.task_d.finished():
                    logger.info("All tasks complete; job done")
                    linger()
                    return 1 if self.task_d.job_failed else 0
                if self.task_d.job_failed:
                    logger.error("Job failed (task retries exhausted)")
                    linger()
                    return 1
                if self.instance_manager is not None:
                    if self.instance_manager.all_workers_failed():
                        logger.error("All workers failed; aborting job")
                        return 1
                    if self.instance_manager.all_workers_done():
                        # Every worker reached a terminal state yet tasks
                        # remain (finished() was checked above): nothing can
                        # make progress.
                        logger.error(
                            "All workers exited but tasks remain; "
                            "aborting job"
                        )
                        return 1
                now = time.time()
                if (
                    now - last_watchdog
                    >= self.args.task_timeout_check_seconds
                ):
                    last_watchdog = now
                    self._run_watchdog()
                    # Journal maintenance rides the watchdog tick: this
                    # thread holds no dispatcher/policy lock here, which
                    # compaction requires (it calls back into the state
                    # providers — see MasterJournal.maybe_compact).
                    if self.journal is not None:
                        self.journal.maybe_compact()
                if self.metrics_service and now - last_metrics >= 30.0:
                    stats = self.task_d.stats()
                    elapsed = now - last_metrics
                    self.metrics_service.log_scalars(
                        "train",
                        self.servicer.max_model_version,
                        {
                            "records_per_sec": (
                                stats["records_done"] - last_records
                            ) / elapsed,
                            "records_done": stats["records_done"],
                            "epoch": stats["epoch"],
                            "todo_tasks": stats["todo"],
                            "doing_tasks": stats["doing"],
                        },
                    )
                    last_metrics = now
                    last_records = stats["records_done"]
                time.sleep(poll)
        finally:
            self.stop()

    def _run_watchdog(self):
        """Task-timeout + liveness watchdog (reference master.py:487-509)."""
        from elasticdl_tpu.master.step_lease import is_lease_owner

        # Synthetic lease owners are excluded: lease lifetime is governed
        # by membership epochs (step_lease.py aborts stale leases), and a
        # watchdog recovery here would yank tasks out from under a live
        # world mid-lease.
        slow = {
            wid
            for wid in self.task_d.doing_tasks_over_timeout()
            if not is_lease_owner(wid)
        }
        deadline = (
            time.time() - self.args.worker_liveness_timeout_seconds
        )
        silent = {
            wid
            for wid, ts in self.servicer.snapshot_liveness().items()
            if ts < deadline
        }
        for worker_id in slow | silent:
            why = "slow" if worker_id in slow else "silent"
            logger.warning(
                "Watchdog: recovering tasks of %s worker %d",
                why,
                worker_id,
            )
            observability.emit_event(
                "task_timeout", worker=worker_id, reason=why
            )
            self.task_d.recover_tasks(worker_id)
            self.servicer.forget_worker(worker_id)
            if self.membership is not None:
                # Drop it from the comm group so survivors re-mesh instead
                # of blocking on the dead rank's next collective.
                self.membership.remove_worker(worker_id)

    def stop(self):
        heartbeat = getattr(self, "_heartbeat", None)
        if heartbeat is not None:
            heartbeat.close()
            self._heartbeat = None
        if self.policy is not None:
            self.policy.close()
            self.policy = None
        if self.aggregator is not None:
            self.aggregator.close()
            self.aggregator = None
        if self.instance_manager is not None:
            self.instance_manager.stop()
        if self.metrics_service is not None:
            # Final snapshot so short jobs (ending inside the periodic
            # interval) still leave a record.
            stats = self.task_d.stats()
            self.metrics_service.log_scalars(
                "train",
                self.servicer.max_model_version,
                {
                    "records_done": stats["records_done"],
                    "epoch": stats["epoch"],
                    "todo_tasks": stats["todo"],
                    "doing_tasks": stats["doing"],
                },
            )
            self.metrics_service.close()
        if self._server is not None:
            self._server.stop(2)
        if getattr(self, "journal", None) is not None:
            self.journal.close()
            self.journal = None
        # Flush + release the per-process trace/event files so a monitor
        # reading them right after exit sees complete lines; also resets
        # the process-global handle for in-process tests that run several
        # masters in one interpreter.
        self.obs.close()
