"""Version-triggered distributed evaluation.

Reference behavior (/root/reference/elasticdl/python/master/
evaluation_service.py:22-175): every time the model version advances past
`eval_steps`, the master creates evaluation tasks; training workers interleave
them, reporting raw model outputs + labels; the master folds those into
streaming metrics and publishes the results when all eval tasks of the job
complete.
"""

import threading

from elasticdl_tpu.common.evaluation_utils import (
    as_metric,
    update_metrics_chunked,
)
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("master.evaluation_service")


class EvaluationJob:
    def __init__(self, metrics, model_version, total_tasks):
        self.model_version = model_version
        self.total_tasks = total_tasks
        self.completed_tasks = 0
        self._metrics = {k: as_metric(v) for k, v in metrics.items()}

    def report_evaluation_metrics(self, outputs, labels):
        update_metrics_chunked(self._metrics, outputs, labels)

    def complete_task(self):
        self.completed_tasks += 1
        return self.completed_tasks >= self.total_tasks

    def results(self):
        return {k: m.result() for k, m in self._metrics.items()}


class EvaluationService:
    def __init__(
        self,
        task_dispatcher,
        eval_metrics_factory,
        eval_steps=0,
        eval_initially=False,
        on_results=None,
    ):
        """eval_metrics_factory: () -> {name: metric}; on_results: callback
        (model_version, {name: value}) when a job finishes (TensorBoard /
        logging hook)."""
        self._task_d = task_dispatcher
        self._metrics_factory = eval_metrics_factory
        self._eval_steps = eval_steps
        # eval_initially: backdate the last-eval marker so the very first
        # report_version already crosses the eval_steps threshold.
        self._last_eval_version = -eval_steps if eval_initially else 0
        self._on_results = on_results
        self._lock = threading.Lock()
        self._job = None
        self.completed_results = []  # [(model_version, {name: value})]
        task_dispatcher.add_evaluation_complete_callback(self._task_completed)

    def add_evaluation_task_if_needed(self, model_version):
        """Called on every report_version (PS version bump or AllReduce step
        report)."""
        with self._lock:
            if self._eval_steps <= 0 or self._job is not None:
                return False
            if model_version < self._last_eval_version + self._eval_steps:
                return False
            n = self._task_d.create_evaluation_tasks(model_version)
            if n == 0:
                return False
            self._job = EvaluationJob(
                self._metrics_factory(), model_version, n
            )
            self._last_eval_version = model_version
            return True

    def start_final_evaluation(self, model_version):
        """One evaluation pass at end of training regardless of eval_steps."""
        with self._lock:
            if self._job is not None:
                return False
            n = self._task_d.create_evaluation_tasks(model_version)
            if n == 0:
                return False
            self._job = EvaluationJob(self._metrics_factory(), model_version, n)
            return True

    def report_evaluation_metrics(self, outputs, labels):
        with self._lock:
            if self._job is None:
                logger.warning("Evaluation metrics reported with no job open")
                return
            self._job.report_evaluation_metrics(outputs, labels)

    def _task_completed(self, task_id, task):
        finished_job = None
        with self._lock:
            if self._job is None:
                return
            if self._job.complete_task():
                finished_job = self._job
                self._job = None
        if finished_job is not None:
            results = finished_job.results()
            self.completed_results.append(
                (finished_job.model_version, results)
            )
            logger.info(
                "Evaluation @ version %d: %s",
                finished_job.model_version,
                results,
            )
            if self._on_results:
                self._on_results(finished_job.model_version, results)

    @property
    def in_progress(self):
        with self._lock:
            return self._job is not None
