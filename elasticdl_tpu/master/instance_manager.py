"""Elastic instance management: start/watch/relaunch worker and PS instances.

Reference counterpart: the k8s InstanceManager
(/root/reference/elasticdl/python/master/k8s_instance_manager.py:53-439),
which creates pods, tracks phases from the watch stream, relaunches
preempted pods, recovers a dead worker's tasks and feeds the alive-worker
set into the rendezvous. The same state machine lives here behind a backend
split:

- LocalProcessInstanceManager: instances are OS subprocesses on this host
  (TPU-VM single-host jobs, tests, and the `edl train --local-cluster`
  path). Exit-code policy mirrors the pod policy: clean exit = done,
  non-zero = failure -> task recovery + relaunch up to the cap.
- K8sInstanceManager (master/k8s_instance_manager.py): pods via the
  kubernetes API, import-gated since the client library/cluster may be
  absent.
"""

import os
import subprocess
import sys
import threading
import time

from elasticdl_tpu.common.constants import PodStatus
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import emit_event
from elasticdl_tpu.observability.metrics import default_registry

logger = get_logger("master.instance_manager")

DEFAULT_MAX_RELAUNCHES = 3

_POD_EVENTS = default_registry().counter(
    "edl_pod_events_total",
    "Instance lifecycle transitions seen by the master",
    labelnames=("kind", "event"),
)


class _Instance:
    def __init__(self, kind, instance_id, popen):
        self.kind = kind  # "worker" | "ps"
        self.id = instance_id
        self.popen = popen
        self.status = PodStatus.RUNNING
        self.relaunch_count = 0
        # Policy-driven deliberate kill: the next exit relaunches without
        # charging the max_relaunches failure budget.
        self.forgive_next_exit = False
        # Policy-driven scale-down: the next exit is a clean retirement
        # (tasks recover, membership drops, no relaunch).
        self.retired = False


class LocalProcessInstanceManager:
    """Spawns worker/PS processes, watches them, relaunches failures.

    command_for(kind, instance_id) -> argv list; the master wires in the
    command builders so this class knows nothing about flags.
    """

    def __init__(
        self,
        command_for,
        num_workers=0,
        num_ps=0,
        task_dispatcher=None,
        membership=None,
        max_relaunches=DEFAULT_MAX_RELAUNCHES,
        poll_seconds=1.0,
        restart_workers=True,
    ):
        self._command_for = command_for
        self._num_workers = num_workers
        self._num_ps = num_ps
        self._task_d = task_dispatcher
        self._membership = membership
        self._max_relaunches = max_relaunches
        self._poll_seconds = poll_seconds
        self._restart_workers = restart_workers
        self._lock = threading.Lock()
        self._instances = {}  # (kind, id) -> _Instance
        self._stop = threading.Event()
        self._monitor = None

    # ---------- lifecycle ----------

    def start_parameter_servers(self):
        for ps_id in range(self._num_ps):
            self._launch("ps", ps_id)

    def start_workers(self):
        for worker_id in range(self._num_workers):
            self._launch("worker", worker_id)
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True
        )
        self._monitor.start()

    def _launch(self, kind, instance_id):
        argv = self._command_for(kind, instance_id)
        # Children get the master's environment (log level/format,
        # observability dir/job, chaos schedule all ride along) plus a
        # per-instance ELASTICDL_ROLE stamp, so every process of one
        # chaos run logs with a correlatable identity.
        env = dict(os.environ)
        env["ELASTICDL_ROLE"] = f"{kind}-{instance_id}"
        # Explicit stamp (not just environ inheritance): every child of
        # this job shares ONE persistent compilation cache, so a
        # relaunched instance rehydrates the executables its previous
        # incarnation (or any peer lowering the same SPMD program)
        # already compiled — the recompile-free preemption path.
        from elasticdl_tpu.common import knobs

        cache_dir = knobs.raw("ELASTICDL_COMPILE_CACHE_DIR")
        if cache_dir:
            env["ELASTICDL_COMPILE_CACHE_DIR"] = cache_dir
        popen = subprocess.Popen(
            argv, stdout=sys.stdout, stderr=sys.stderr, env=env
        )
        with self._lock:
            prev = self._instances.get((kind, instance_id))
            inst = _Instance(kind, instance_id, popen)
            if prev is not None:
                inst.relaunch_count = prev.relaunch_count
            self._instances[(kind, instance_id)] = inst
        _POD_EVENTS.labels(kind=kind, event="launch").inc()
        emit_event(
            "pod_launch", instance=f"{kind}-{instance_id}", pid=popen.pid
        )
        logger.info("Launched %s %d (pid %d)", kind, instance_id, popen.pid)

    def stop(self):
        self._stop.set()
        with self._lock:
            instances = list(self._instances.values())
        for inst in instances:
            if inst.popen.poll() is None:
                inst.popen.terminate()
        deadline = time.time() + 10
        for inst in instances:
            try:
                inst.popen.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                inst.popen.kill()

    # ---------- watch / relaunch (the elastic engine) ----------

    def _monitor_loop(self):
        while not self._stop.is_set():
            with self._lock:
                instances = list(self._instances.values())
            for inst in instances:
                code = inst.popen.poll()
                if code is None or inst.status in (
                    PodStatus.SUCCEEDED,
                    PodStatus.FAILED,
                ):
                    continue
                self._on_exit(inst, code)
            self._stop.wait(self._poll_seconds)

    def _on_exit(self, inst, code):
        if self._stop.is_set():
            # Teardown in progress: exits are stop()'s own SIGTERMs, not
            # failures — relaunching here would leak processes.
            return
        _POD_EVENTS.labels(kind=inst.kind, event="exit").inc()
        emit_event(
            "pod_exit",
            instance=f"{inst.kind}-{inst.id}",
            exit_code=code,
        )
        if inst.retired:
            # Deliberate scale-down: the exit is the retirement completing,
            # whatever the exit code. Tasks recover, membership drops, and
            # the instance counts as done — never as a failure.
            inst.status = PodStatus.SUCCEEDED
            logger.info("%s %d retired (scale-down)", inst.kind, inst.id)
            if inst.kind == "worker":
                if self._task_d is not None:
                    self._task_d.recover_tasks(inst.id)
                if self._membership is not None:
                    self._membership.remove_worker(inst.id)
            return
        if code == 0:
            inst.status = PodStatus.SUCCEEDED
            logger.info("%s %d finished", inst.kind, inst.id)
            if inst.kind == "worker" and self._membership is not None:
                self._membership.remove_worker(inst.id)
            return
        logger.warning(
            "%s %d exited with code %d", inst.kind, inst.id, code
        )
        if inst.kind == "worker":
            # Recover its in-flight tasks FIRST so they re-dispatch
            # (reference k8s_instance_manager.py:320-325), then drop it
            # from the comm group so survivors re-mesh.
            if self._task_d is not None:
                self._task_d.recover_tasks(inst.id)
            if self._membership is not None:
                self._membership.remove_worker(inst.id)
        forgiven = inst.forgive_next_exit
        inst.forgive_next_exit = False
        relaunch = (
            forgiven or inst.relaunch_count < self._max_relaunches
        ) and (inst.kind == "ps" or self._restart_workers)
        if relaunch:
            if not forgiven:
                inst.relaunch_count += 1
            logger.info(
                "Relaunching %s %d (attempt %d)",
                inst.kind,
                inst.id,
                inst.relaunch_count,
            )
            _POD_EVENTS.labels(kind=inst.kind, event="relaunch").inc()
            emit_event(
                "pod_relaunch",
                instance=f"{inst.kind}-{inst.id}",
                attempt=inst.relaunch_count,
            )
            self._launch(inst.kind, inst.id)
            with self._lock:
                self._instances[(inst.kind, inst.id)].relaunch_count = (
                    inst.relaunch_count
                )
        else:
            inst.status = PodStatus.FAILED
            _POD_EVENTS.labels(kind=inst.kind, event="failed").inc()
            emit_event(
                "pod_failed",
                instance=f"{inst.kind}-{inst.id}",
                exit_code=code,
            )

    # ---------- policy actuators ----------

    def restart_worker(self, worker_id, reason=""):
        """Deliberate kill+relaunch of one worker (straggler mitigation).
        The monitor loop performs the relaunch on its next poll; the exit
        is forgiven, so mitigation never consumes the max_relaunches
        failure budget. Returns False when the worker isn't running."""
        with self._lock:
            inst = self._instances.get(("worker", worker_id))
            if (
                inst is None
                or inst.retired
                or inst.popen.poll() is not None
            ):
                return False
            inst.forgive_next_exit = True
        _POD_EVENTS.labels(kind="worker", event="restart").inc()
        emit_event(
            "pod_restart",
            instance=f"worker-{worker_id}",
            reason=reason[:200],
        )
        logger.info("Restarting worker %d (%s)", worker_id, reason)
        inst.popen.terminate()
        return True

    def scale_workers(self, delta, reason=""):
        """Policy-driven ±k worker scaling. Positive delta launches new
        worker ids past the current highest; negative retires the
        highest-id running workers (tasks recover, membership drops, no
        relaunch). Returns the affected worker ids."""
        if delta == 0:
            return []
        affected = []
        if delta > 0:
            with self._lock:
                worker_ids = [
                    i.id
                    for i in self._instances.values()
                    if i.kind == "worker"
                ]
                next_id = (max(worker_ids) + 1) if worker_ids else 0
                self._num_workers = max(
                    self._num_workers, next_id + delta
                )
            for wid in range(next_id, next_id + delta):
                self._launch("worker", wid)
                affected.append(wid)
        else:
            with self._lock:
                victims = sorted(
                    (
                        i
                        for i in self._instances.values()
                        if i.kind == "worker"
                        and not i.retired
                        and i.status == PodStatus.RUNNING
                    ),
                    key=lambda i: -i.id,
                )[:-delta]
                for inst in victims:
                    inst.retired = True
                self._num_workers = max(
                    0, self._num_workers - len(victims)
                )
            for inst in victims:
                affected.append(inst.id)
                if inst.popen.poll() is None:
                    inst.popen.terminate()
        if affected:
            event = "scale_up" if delta > 0 else "scale_down"
            _POD_EVENTS.labels(kind="worker", event=event).inc(
                len(affected)
            )
            emit_event(
                "pod_scale",
                delta=delta,
                workers=affected,
                reason=reason[:200],
            )
            logger.info(
                "Scaled workers %+d (%s): %s", delta, reason, affected
            )
        return affected

    def worker_count(self):
        """Workers currently part of the job (running or pending relaunch;
        retired and terminally failed ones excluded)."""
        with self._lock:
            return sum(
                1
                for i in self._instances.values()
                if i.kind == "worker"
                and not i.retired
                and i.status != PodStatus.FAILED
            )

    # ---------- status ----------

    def all_workers_failed(self):
        with self._lock:
            workers = [
                i for i in self._instances.values() if i.kind == "worker"
            ]
        return bool(workers) and all(
            w.status == PodStatus.FAILED for w in workers
        )

    def all_workers_done(self):
        with self._lock:
            workers = [
                i for i in self._instances.values() if i.kind == "worker"
            ]
        return bool(workers) and all(
            w.status in (PodStatus.SUCCEEDED, PodStatus.FAILED)
            for w in workers
        )

    def worker_statuses(self):
        with self._lock:
            return {
                i.id: i.status
                for i in self._instances.values()
                if i.kind == "worker"
            }

    def total_relaunches(self):
        """Cumulative relaunches across all instances (job-status RPC)."""
        with self._lock:
            return sum(
                i.relaunch_count for i in self._instances.values()
            )
