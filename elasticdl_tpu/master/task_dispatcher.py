"""Dynamic data sharding: the master's task state machine.

Re-implementation of the reference dispatcher's behavior
(/root/reference/elasticdl/python/master/task_dispatcher.py:77-392): the
dataset is partitioned into record-range tasks; workers pull tasks and report
completion; failed tasks are re-queued up to MAX_TASK_RETRIES; a dead worker's
in-flight tasks are recovered; training tasks regenerate per epoch. This is
what makes training elastic without checkpoint-restart — task assignment is
the only distributed state, and it lives here.

The state machine is framework-agnostic by design (no JAX/TF imports).
"""

import collections
import random
import threading
import time

from elasticdl_tpu.common.constants import MAX_TASK_RETRIES
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import emit_event
from elasticdl_tpu.observability.metrics import default_registry
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

logger = get_logger("master.task_dispatcher")

_REG = default_registry()
_DISPATCHED = _REG.counter(
    "edl_tasks_dispatched_total",
    "Tasks handed to workers",
    labelnames=("type",),
)
_REPORTED = _REG.counter(
    "edl_tasks_reported_total",
    "Task completions by result",
    labelnames=("result",),
)
_RECOVERED = _REG.counter(
    "edl_tasks_recovered_total",
    "In-flight tasks requeued after worker death/timeouts",
)
_ABANDONED = _REG.counter(
    "edl_tasks_abandoned_total",
    "Tasks dropped after exhausting max_task_retries (fails the job)",
)
_BACKUPS = _REG.counter(
    "edl_backup_tasks_total",
    "Speculative backup task copies, by lifecycle outcome",
    labelnames=("outcome",),
)
_BLACKLISTED = _REG.gauge(
    "edl_workers_blacklisted",
    "Workers currently blacklisted by the dispatcher (no new tasks)",
)
_TODO = _REG.gauge("edl_tasks_todo", "Tasks waiting for dispatch")
_DOING = _REG.gauge("edl_tasks_doing", "Tasks currently in flight")
_RECORDS = _REG.gauge(
    "edl_records_done", "Training records successfully processed"
)
# Control-plane latency: time spent inside the dispatcher's lock per
# operation. Sub-millisecond buckets — at 500 workers the dispatch path
# runs thousands of times a second and this histogram is how the fleet
# harness proves it stays flat.
_DISPATCH_SECONDS = _REG.histogram(
    "edl_master_dispatch_seconds",
    "Task dispatcher critical-section latency, by operation",
    labelnames=("op",),
    buckets=(
        0.00001, 0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05,
        0.1, 0.5, 1.0,
    ),
)


def _type_name(task_type):
    try:
        return pb.TaskType.Name(task_type)
    except ValueError:
        return str(task_type)


class _Task:
    """A record range [start, end) in a named shard, plus retry accounting."""

    def __init__(self, shard_name, start, end, task_type, model_version=-1):
        self.shard_name = shard_name
        self.start = start
        self.end = end
        self.type = task_type
        self.model_version = model_version
        self.retry_count = 0

    def to_proto(self, task_id):
        return pb.Task(
            task_id=task_id,
            shard_name=self.shard_name,
            start=self.start,
            end=self.end,
            type=self.type,
            model_version=self.model_version,
        )

    def __repr__(self):
        return (
            f"_Task({self.shard_name}[{self.start}:{self.end}] "
            f"type={self.type} v={self.model_version})"
        )


def _task_to_tuple(task):
    """Journal wire form of a task: a plain JSON list (see journal.py)."""
    return [
        task.shard_name, task.start, task.end, int(task.type),
        task.model_version, task.retry_count,
    ]


def _task_from_tuple(t):
    task = _Task(t[0], t[1], t[2], t[3], t[4])
    task.retry_count = t[5]
    return task


class TaskDispatcher:
    """Thread-safe todo/doing task queues with elastic recovery."""

    def __init__(
        self,
        training_shards,
        evaluation_shards=None,
        prediction_shards=None,
        records_per_task=1024,
        num_epochs=1,
        shuffle=True,
        max_task_retries=MAX_TASK_RETRIES,
        seed=None,
    ):
        """Shard dicts map shard_name -> (start_index, num_records)."""
        self._lock = threading.Lock()
        self._training_shards = dict(training_shards or {})
        self._evaluation_shards = dict(evaluation_shards or {})
        self._prediction_shards = dict(prediction_shards or {})
        self._records_per_task = records_per_task
        self._num_epochs = num_epochs
        self._shuffle = shuffle
        self._max_task_retries = max_task_retries
        self._rng = random.Random(seed)

        self._epoch = 0
        self._next_task_id = 0
        self._todo = collections.deque()  # _Task queue, consumed from the front
        self._doing = {}  # task_id -> (worker_id, _Task, start_time)
        self._job_failed = False
        self._stop_training = False
        self._train_end_pending = False
        # Rolling completion-time stats per task type, for the timeout
        # watchdog (reference master/servicer.py:131-148).
        self._task_durations = {}  # task_type -> deque of seconds (bounded)
        self._records_done = 0  # successful TRAINING records, for monitors
        self._tasks_recovered = 0  # cumulative, for the job-status RPC
        self._tasks_abandoned = 0  # retry-exhausted drops, ditto
        self._eval_complete_callbacks = []
        self._tasks_done_callbacks = []
        # Policy plane: blacklist + speculative backup copies.
        self._blacklist = {}  # worker_id -> (expires_at, reason)
        self._backup_queue = collections.deque()  # primary ids needing a copy
        self._twins = {}  # task_id <-> twin task_id (both directions)
        self._backup_ids = set()  # ids in _doing that are backup copies
        # Copies retired because their twin won the race: the loser's late
        # report is acknowledged-but-discarded instead of warned about.
        # Entries leave on use or with the job, and the set is bounded by
        # the backup rate limit — at most one per launched backup.
        self._retired_twins = set()
        self._backups_launched = 0
        self._backup_wins = 0
        # Survivable control plane (PR 19): monotonic lease tokens defend
        # result reports across a master restart, and every mutation below
        # is mirrored into the attached write-ahead journal BEFORE the RPC
        # ack (attach_journal). No journal attached -> zero overhead.
        self._journal = None
        self._next_lease_token = 0
        self._lease_tokens = {}  # task_id -> token, lives with _doing

        if self._training_shards:
            logger.info("Starting epoch 0")
            self._epoch = 1
            self._create_tasks_locked(pb.TRAINING)
        elif self._evaluation_shards:
            self._create_tasks_locked(pb.EVALUATION)
        elif self._prediction_shards:
            self._create_tasks_locked(pb.PREDICTION)

    # ---------- journal plane ----------

    def attach_journal(self, journal):
        """Mirror every mutation into the write-ahead journal from now on.

        Call AFTER construction-time setup (initial task creation,
        set_completed_records fast-forward, restore_state): the caller
        snapshots immediately after attaching, so the WAL only ever holds
        post-start ops and replay never has to re-derive RNG shuffles."""
        with self._lock:
            self._journal = journal

    def _j(self, op):
        """Append one op to the journal (write-ahead: callers hold the
        dispatch lock, so the op lands before the RPC ack leaves)."""
        if self._journal is not None:
            self._journal.record(op)

    def lease_token(self, task_id):
        """The token stamped into the dispatched Task proto (0 = no lease)."""
        with self._lock:
            return self._lease_tokens.get(task_id, 0)

    def export_state(self):
        """Journal-snapshot slice of the dispatcher state (journal.py's
        vocabulary; JSON-safe)."""
        with self._lock:
            return {
                "next_task_id": self._next_task_id,
                "next_lease_token": self._next_lease_token,
                "epoch": self._epoch,
                "todo": [_task_to_tuple(t) for t in self._todo],
                "doing": {
                    str(tid): {
                        "worker": wid,
                        "task": _task_to_tuple(task),
                        "token": self._lease_tokens.get(tid, 0),
                    }
                    for tid, (wid, task, _) in self._doing.items()
                },
                "records_done": self._records_done,
                "tasks_recovered": self._tasks_recovered,
                "tasks_abandoned": self._tasks_abandoned,
                "job_failed": self._job_failed,
                "stop_training": self._stop_training,
                "train_end_pending": self._train_end_pending,
                "twins": {str(k): v for k, v in self._twins.items()},
                "backup_ids": sorted(self._backup_ids),
                "retired_twins": sorted(self._retired_twins),
                "backups_launched": self._backups_launched,
                "backup_wins": self._backup_wins,
                "blacklist": {
                    str(wid): [expires_at, reason]
                    for wid, (expires_at, reason) in self._blacklist.items()
                },
            }

    def restore_state(self, state):
        """Load a replayed journal state (journal.replay output). In-flight
        leases are restored with a RECOVERY-TIME start so the watchdog
        grants reappearing owners a fresh grace window and sweeps the rest;
        the caller emits the lease_reissued trail."""
        now = time.time()
        with self._lock:
            self._epoch = int(state["epoch"])
            self._next_task_id = int(state["next_task_id"])
            self._next_lease_token = int(state["next_lease_token"])
            self._todo = collections.deque(
                _task_from_tuple(t) for t in state["todo"]
            )
            self._doing = {}
            self._lease_tokens = {}
            for tid, entry in state["doing"].items():
                tid = int(tid)
                self._doing[tid] = (
                    entry["worker"], _task_from_tuple(entry["task"]), now
                )
                self._lease_tokens[tid] = int(entry.get("token", 0))
            self._records_done = int(state["records_done"])
            self._tasks_recovered = int(state["tasks_recovered"])
            self._tasks_abandoned = int(state["tasks_abandoned"])
            self._job_failed = bool(state["job_failed"])
            self._stop_training = bool(state["stop_training"])
            self._train_end_pending = bool(state["train_end_pending"])
            self._twins = {
                int(k): int(v) for k, v in state.get("twins", {}).items()
            }
            self._backup_ids = set(state.get("backup_ids", []))
            self._retired_twins = set(state.get("retired_twins", []))
            self._backups_launched = int(state.get("backups_launched", 0))
            self._backup_wins = int(state.get("backup_wins", 0))
            self._blacklist = {
                int(wid): (float(v[0]), str(v[1]))
                for wid, v in state.get("blacklist", {}).items()
            }
            _BLACKLISTED.set(len(self._blacklist))
            self._gauges_locked()

    def inflight_leases(self):
        """[(task_id, worker_id, _Task)] snapshot, for the recovery trail."""
        with self._lock:
            return [
                (tid, wid, task)
                for tid, (wid, task, _) in self._doing.items()
            ]

    # ---------- task creation ----------

    def _shards_for(self, task_type):
        return {
            pb.TRAINING: self._training_shards,
            pb.EVALUATION: self._evaluation_shards,
            pb.PREDICTION: self._prediction_shards,
        }[task_type]

    def _create_tasks_locked(self, task_type, model_version=-1, at_front=False):
        tasks = []
        for name, (start, num_records) in self._shards_for(task_type).items():
            for begin in range(start, start + num_records, self._records_per_task):
                end = min(begin + self._records_per_task, start + num_records)
                tasks.append(_Task(name, begin, end, task_type, model_version))
        if task_type == pb.TRAINING and self._shuffle:
            self._rng.shuffle(tasks)
        if at_front:
            # extendleft reverses; pre-reverse to preserve task order.
            self._todo.extendleft(reversed(tasks))
        else:
            self._todo.extend(tasks)
        self._gauges_locked()
        if tasks:
            self._j({
                "op": "tasks_created",
                "epoch": self._epoch,
                "at_front": at_front,
                "tasks": [_task_to_tuple(t) for t in tasks],
            })
            emit_event(
                "task_create",
                type=_type_name(task_type),
                count=len(tasks),
                epoch=self._epoch,
            )
        return len(tasks)

    def _gauges_locked(self):
        _TODO.set(len(self._todo))
        _DOING.set(len(self._doing))
        _RECORDS.set(self._records_done)

    def set_completed_records(self, records):
        """Fast-forward past already-trained data on restart-from-checkpoint
        (reference master.py:185-201 restores the completed-step count into
        MaxStepsStopping so finished work is not re-dispatched). Whole
        epochs are skipped exactly; the partial epoch is trimmed from the
        front of the current (shuffled) task queue. Call before any worker
        pulls a task."""
        with self._lock:
            if not self._training_shards or records <= 0 or self._doing:
                return 0
            epoch_records = sum(
                n for _, n in self._training_shards.values()
            )
            full_epochs = min(records // epoch_records, self._num_epochs)
            remainder = (
                0
                if full_epochs >= self._num_epochs
                else records - full_epochs * epoch_records
            )
            if full_epochs >= self._num_epochs:
                # Everything already trained: drain training work.
                self._todo = collections.deque(
                    t for t in self._todo if t.type != pb.TRAINING
                )
                self._epoch = self._num_epochs
            elif full_epochs:
                self._epoch = full_epochs + 1
                # The queue currently holds epoch 1's permutation, but the
                # interrupted run was consuming epoch full_epochs+1's — and
                # each epoch rollover advanced the shared shuffle RNG once.
                # Regenerate full_epochs times (discarding all but the
                # last) so the trim below removes the records the original
                # run actually trained.
                self._todo = collections.deque(
                    t for t in self._todo if t.type != pb.TRAINING
                )
                for i in range(full_epochs):
                    n = self._create_tasks_locked(pb.TRAINING)
                    if i < full_epochs - 1:
                        for _ in range(n):
                            self._todo.pop()
            skipped = full_epochs * epoch_records
            if remainder:
                kept = collections.deque()
                for task in self._todo:
                    if task.type != pb.TRAINING or remainder <= 0:
                        kept.append(task)
                        continue
                    size = task.end - task.start
                    if remainder >= size:
                        remainder -= size
                        skipped += size
                    else:
                        task.start += remainder
                        skipped += remainder
                        remainder = 0
                        kept.append(task)
                self._todo = kept
            if skipped:
                # Seed the cumulative counter so monitors/metrics continue
                # from the pre-restart figure instead of restarting at 0.
                self._records_done += skipped
                logger.info(
                    "Resume: skipping %d already-trained records "
                    "(%d full epochs)",
                    skipped,
                    full_epochs,
                )
            return skipped

    def create_evaluation_tasks(self, model_version):
        """Version-triggered eval: tasks go to the FRONT of the queue so
        training workers pick them up promptly."""
        with self._lock:
            n = self._create_tasks_locked(
                pb.EVALUATION, model_version, at_front=True
            )
        logger.info(
            "Created %d evaluation tasks at model version %d", n, model_version
        )
        return n

    def enable_train_end_task(self):
        """Arm a final TRAIN_END_CALLBACK task (model export) dispatched
        exactly once, after all training work drains. The task materializes
        lazily inside finished() so it cannot be picked up mid-epoch."""
        with self._lock:
            self._train_end_pending = bool(self._training_shards)
            self._j({
                "op": "train_end_enabled",
                "pending": self._train_end_pending,
            })

    # ---------- worker-facing operations ----------

    def _roll_epoch_locked(self, drained):
        """One epoch-rollover state machine for both pop paths: when the
        caller-supplied drain condition holds and epochs remain, generate
        the next epoch's (shuffled) training tasks."""
        if (
            drained
            and not self._stop_training
            and self._epoch < self._num_epochs
            and self._training_shards
        ):
            logger.info("Starting epoch %d", self._epoch)
            self._epoch += 1
            self._create_tasks_locked(pb.TRAINING)

    def get(self, worker_id):
        """Pop the next task for a worker; () epoch rollover when the
        training queue drains. Returns (task_id, _Task) or (-1, None)."""
        t0 = time.perf_counter()
        try:
            with self._lock:
                if self._blacklisted_locked(worker_id):
                    return -1, None
                backup = self._serve_backup_locked(worker_id)
                if backup is not None:
                    return backup
                self._roll_epoch_locked(not self._todo)
                if not self._todo:
                    return -1, None
                task = self._todo.popleft()
                task_id = self._next_task_id
                self._next_task_id += 1
                self._doing[task_id] = (worker_id, task, time.time())
                self._next_lease_token += 1
                self._lease_tokens[task_id] = self._next_lease_token
                self._j({
                    "op": "lease",
                    "task_id": task_id,
                    "worker": worker_id,
                    "task": _task_to_tuple(task),
                    "token": self._next_lease_token,
                })
                _DISPATCHED.labels(type=_type_name(task.type)).inc()
                self._gauges_locked()
                return task_id, task
        finally:
            _DISPATCH_SECONDS.labels(op="get").observe(
                time.perf_counter() - t0
            )

    def get_batch(self, worker_id, max_tasks):
        """Lease up to max_tasks tasks in one call: [(task_id, _Task)].
        Shares get()'s blacklist/backup/epoch semantics per popped task."""
        tasks = []
        for _ in range(max(1, max_tasks)):
            task_id, task = self.get(worker_id)
            if task_id < 0:
                break
            tasks.append((task_id, task))
        return tasks

    def get_eval_task(self, worker_id):
        """Pop the first EVALUATION task only (reference
        task_dispatcher.py:272-297)."""
        return self.get_typed(worker_id, pb.EVALUATION)

    def get_typed(self, worker_id, task_type):
        """Pop the first task of one type only. For TRAINING this also
        rolls the epoch when the training queue drains (the step-lease
        manager consumes training work through here while evaluation tasks
        stay available to get_eval_task)."""
        t0 = time.perf_counter()
        try:
            with self._lock:
                if self._blacklisted_locked(worker_id):
                    return -1, None
                if task_type == pb.TRAINING:
                    backup = self._serve_backup_locked(worker_id)
                    if backup is not None:
                        return backup
                    self._roll_epoch_locked(
                        not any(t.type == pb.TRAINING for t in self._todo)
                    )
                for i, task in enumerate(self._todo):
                    if task.type == task_type:
                        del self._todo[i]
                        task_id = self._next_task_id
                        self._next_task_id += 1
                        self._doing[task_id] = (
                            worker_id, task, time.time()
                        )
                        self._next_lease_token += 1
                        self._lease_tokens[task_id] = self._next_lease_token
                        self._j({
                            "op": "lease",
                            "task_id": task_id,
                            "worker": worker_id,
                            "task": _task_to_tuple(task),
                            "token": self._next_lease_token,
                        })
                        _DISPATCHED.labels(
                            type=_type_name(task.type)
                        ).inc()
                        self._gauges_locked()
                        return task_id, task
                return -1, None
        finally:
            _DISPATCH_SECONDS.labels(op="get").observe(
                time.perf_counter() - t0
            )

    # ---------- policy plane: blacklist + speculative backups ----------

    def _blacklisted_locked(self, worker_id, now=None):
        entry = self._blacklist.get(worker_id)
        if entry is None:
            return False
        expires_at, _ = entry
        if (now or time.time()) >= expires_at:
            # TTL expiry re-admits the worker even if its relaunch never
            # completed — the self-healing default.
            del self._blacklist[worker_id]
            _BLACKLISTED.set(len(self._blacklist))
            return False
        return True

    def blacklist_worker(self, worker_id, ttl_seconds, reason=""):
        """No new task routes to this worker until the TTL expires or
        unblacklist_worker is called. In-flight tasks are untouched (the
        caller decides whether to recover them)."""
        with self._lock:
            until = time.time() + max(ttl_seconds, 0.0)
            self._blacklist[worker_id] = (until, reason)
            self._j({
                "op": "blacklist",
                "worker": worker_id,
                "until": until,
                "reason": reason[:200],
            })
            _BLACKLISTED.set(len(self._blacklist))
        emit_event(
            "worker_blacklist",
            worker=worker_id,
            ttl_seconds=round(ttl_seconds, 1),
            reason=reason[:200],
        )
        logger.info(
            "Blacklisted worker %d for %.0fs (%s)",
            worker_id, ttl_seconds, reason,
        )

    def unblacklist_worker(self, worker_id):
        with self._lock:
            removed = self._blacklist.pop(worker_id, None) is not None
            if removed:
                self._j({"op": "unblacklist", "worker": worker_id})
            _BLACKLISTED.set(len(self._blacklist))
        if removed:
            emit_event("worker_blacklist", worker=worker_id, cleared=True)
        return removed

    def blacklisted_workers(self):
        """Currently blacklisted worker ids (expired entries dropped)."""
        now = time.time()
        with self._lock:
            return sorted(
                wid for wid in list(self._blacklist)
                if self._blacklisted_locked(wid, now)
            )

    def backup_candidates(self, factor=3.0, min_samples=5, limit=1):
        """In-flight TRAINING tasks running > factor x the rolling mean
        completion time with no backup copy yet, slowest first:
        [(task_id, worker_id, elapsed_seconds)]."""
        now = time.time()
        with self._lock:
            durations = self._task_durations.get(pb.TRAINING, [])
            if len(durations) < min_samples:
                return []
            mean = max(sum(durations) / len(durations), 1e-3)
            queued = set(self._backup_queue)
            out = []
            for tid, (wid, task, start) in self._doing.items():
                if task.type != pb.TRAINING:
                    continue
                if tid in self._twins or tid in self._backup_ids:
                    continue
                if tid in queued:
                    continue
                elapsed = now - start
                if elapsed > factor * mean:
                    out.append((tid, wid, elapsed))
            out.sort(key=lambda item: -item[2])
            return out[:limit]

    def request_backup(self, task_id):
        """Queue a speculative second copy of an in-flight TRAINING task.
        The copy goes to the next eligible worker that asks for work (never
        the primary's owner); first result wins, the loser's late report is
        acknowledged and discarded, records_done counts once."""
        with self._lock:
            entry = self._doing.get(task_id)
            if (
                entry is None
                or entry[1].type != pb.TRAINING
                or task_id in self._twins
                or task_id in self._backup_ids
                or task_id in self._backup_queue
            ):
                return False
            self._backup_queue.append(task_id)
        _BACKUPS.labels(outcome="requested").inc()
        emit_event("backup_task", task_id=task_id, phase="requested")
        return True

    def _serve_backup_locked(self, worker_id):
        """Hand a queued backup copy to worker_id if one is eligible (the
        primary is still in flight and owned by someone else). Returns
        (backup_task_id, _Task) or None."""
        for _ in range(len(self._backup_queue)):
            primary_id = self._backup_queue.popleft()
            entry = self._doing.get(primary_id)
            if entry is None or primary_id in self._twins:
                continue  # primary resolved (or raced) while queued
            owner_id, task, _ = entry
            if owner_id == worker_id:
                # Never give the straggler its own backup; retry later.
                self._backup_queue.append(primary_id)
                continue
            backup_id = self._next_task_id
            self._next_task_id += 1
            self._doing[backup_id] = (worker_id, task, time.time())
            self._twins[primary_id] = backup_id
            self._twins[backup_id] = primary_id
            self._backup_ids.add(backup_id)
            self._backups_launched += 1
            self._next_lease_token += 1
            self._lease_tokens[backup_id] = self._next_lease_token
            self._j({
                "op": "backup_lease",
                "task_id": backup_id,
                "primary_id": primary_id,
                "worker": worker_id,
                "task": _task_to_tuple(task),
                "token": self._next_lease_token,
            })
            _DISPATCHED.labels(type=_type_name(task.type)).inc()
            _BACKUPS.labels(outcome="dispatched").inc()
            self._gauges_locked()
            emit_event(
                "backup_task",
                task_id=primary_id,
                backup_id=backup_id,
                phase="dispatched",
                worker=worker_id,
                primary_worker=owner_id,
            )
            return backup_id, task
        return None

    def _resolve_twin_locked(self, task_id, success):
        """First-result-wins bookkeeping for a reported copy of a twinned
        task. Returns (verdict, twin_id): "win" (count this report's
        records), "lone_failure" (no live twin: run the normal retry
        ladder), or "copy_failed" (this copy failed but its twin is still
        racing: discard). twin_id is the retired twin, None when untwinned."""
        twin_id = self._twins.pop(task_id, None)
        if twin_id is None:
            return ("win" if success else "lone_failure"), None
        self._twins.pop(twin_id, None)
        if success:
            # Retire the losing copy: its in-flight entry leaves _doing
            # now and its eventual late report is ack-and-discard.
            if self._doing.pop(twin_id, None) is not None:
                self._retired_twins.add(twin_id)
                self._backup_ids.discard(twin_id)
                self._lease_tokens.pop(twin_id, None)
            self._backup_wins += 1
            outcome = (
                "backup_win" if task_id in self._backup_ids
                else "primary_win"
            )
            _BACKUPS.labels(outcome=outcome).inc()
            emit_event(
                "backup_task",
                task_id=task_id,
                twin=twin_id,
                phase=outcome,
            )
            return "win", twin_id
        # This copy failed but the twin is still running: the twin owns
        # the work now (requeueing here would triple-run the range).
        _BACKUPS.labels(outcome="copy_failed").inc()
        emit_event(
            "backup_task", task_id=task_id, twin=twin_id,
            phase="copy_failed",
        )
        return "copy_failed", twin_id

    def report(self, task_id, success, err_message="", lease_token=0):
        """Worker finished (or failed) a task. Failed tasks are re-queued at
        the front until retries are exhausted, which fails the job.

        lease_token defends exactly-once accounting across master restarts:
        a nonzero token that mismatches the stored lease is a report for a
        lease this incarnation never issued (or already resolved) — it is
        acknowledged and discarded. Token 0 is the legacy/no-journal path
        and is always accepted."""
        t0 = time.perf_counter()
        try:
            return self._report_timed(task_id, success, err_message,
                                      lease_token)
        finally:
            _DISPATCH_SECONDS.labels(op="report").observe(
                time.perf_counter() - t0
            )

    def _report_timed(self, task_id, success, err_message="", lease_token=0):
        with self._lock:
            if lease_token:
                stored = self._lease_tokens.get(task_id)
                if stored is not None and stored != lease_token:
                    # Stale lease: the report belongs to a superseded lease
                    # of the same task id (re-issued after recovery). Ack
                    # and discard — the live lease owns the accounting.
                    _REPORTED.labels(result="stale_lease").inc()
                    emit_event(
                        "task_stale_lease", task_id=task_id,
                        token=lease_token, expected=stored,
                    )
                    return None
            entry = self._doing.pop(task_id, None)
            if entry is None:
                if task_id in self._retired_twins:
                    # The loser of a backup race reporting late: its twin
                    # already won and took the accounting. Acknowledge and
                    # discard — records_done must never double-count.
                    self._retired_twins.discard(task_id)
                    _REPORTED.labels(result="duplicate").inc()
                    emit_event(
                        "backup_task", task_id=task_id,
                        phase="late_duplicate",
                    )
                    return None
                logger.warning("Unknown task id reported: %d", task_id)
                return None
            self._lease_tokens.pop(task_id, None)
            worker_id, task, start_time = entry
            verdict, twin_id = self._resolve_twin_locked(task_id, success)
            self._backup_ids.discard(task_id)
            if verdict == "copy_failed":
                # Failed copy of a still-racing twin: no retry ladder.
                self._j({"op": "dropped", "task_id": task_id})
                self._gauges_locked()
                return task
            if success:
                _REPORTED.labels(result="success").inc()
                self._task_durations.setdefault(
                    task.type, collections.deque(maxlen=100)
                ).append(time.time() - start_time)
                if task.type == pb.TRAINING:
                    self._records_done += task.end - task.start
                self._j({
                    "op": "done",
                    "task_id": task_id,
                    "records": (
                        task.end - task.start
                        if task.type == pb.TRAINING else 0
                    ),
                    "retire_twin": twin_id,
                    "backup_win": twin_id is not None,
                })
                evaluation_done = task.type == pb.EVALUATION
                job_done = self._finished_locked()
            elif self._stop_training and task.type == pb.TRAINING:
                # Early stop: don't resurrect failed training tasks.
                self._j({"op": "dropped", "task_id": task_id})
                evaluation_done = False
                job_done = self._finished_locked()
            else:
                _REPORTED.labels(result="failure").inc()
                task.retry_count += 1
                if task.retry_count > self._max_task_retries:
                    logger.error(
                        "Task %s failed %d times (last: %s); abandoning "
                        "it and failing the job",
                        task,
                        task.retry_count,
                        err_message,
                    )
                    self._abandon_locked(task, task_id, worker_id,
                                         err_message)
                    emit_event(
                        "job_failed",
                        task_id=task_id,
                        worker=worker_id,
                        error=err_message[:200],
                    )
                    # Terminal: drop remaining work so workers drain and
                    # exit; the master process checks job_failed.
                    self._todo.clear()
                else:
                    logger.warning(
                        "Re-queueing failed task %s (%s)", task, err_message
                    )
                    emit_event(
                        "task_failed",
                        task_id=task_id,
                        worker=worker_id,
                        retry=task.retry_count,
                        error=err_message[:200],
                    )
                    self._todo.appendleft(task)
                    self._j({
                        "op": "failed_requeue",
                        "task_id": task_id,
                        "task": _task_to_tuple(task),
                    })
                evaluation_done = False
                job_done = False
            self._gauges_locked()
        # Callbacks run outside the lock: they may call back into us.
        if success and evaluation_done:
            for cb in self._eval_complete_callbacks:
                cb(task_id, task)
        if success and job_done:
            for cb in self._tasks_done_callbacks:
                cb()
        return task

    def fail_owner_tasks(self, owner_id, err_message=""):
        """Requeue every in-flight task of an owner THROUGH the retry
        ladder (unlike recover_tasks, which requeues for free). Used for
        fault-attributed lease aborts: a deterministic per-range failure
        must exhaust max_task_retries and fail the job, exactly as the
        same error would on the non-lease path, instead of relenting
        forever."""
        failed = []
        with self._lock:
            ids = [
                tid
                for tid, (wid, _, _) in self._doing.items()
                if wid == owner_id
            ]
            for tid in ids:
                _, task, _ = self._doing.pop(tid)
                self._lease_tokens.pop(tid, None)
                if self._drop_copy_if_twinned_locked(tid):
                    self._j({"op": "dropped", "task_id": tid})
                    continue
                if self._stop_training and task.type == pb.TRAINING:
                    self._j({"op": "dropped", "task_id": tid})
                    continue
                task.retry_count += 1
                if task.retry_count > self._max_task_retries:
                    failed.append(task)
                    self._abandon_locked(task, tid, owner_id, err_message)
                    self._todo.clear()
                else:
                    self._todo.appendleft(task)
                    self._j({
                        "op": "failed_requeue",
                        "task_id": tid,
                        "task": _task_to_tuple(task),
                    })
            self._gauges_locked()
        for task in failed:
            logger.error(
                "Task %s failed %d times (last: %s); failing job",
                task,
                task.retry_count,
                err_message,
            )
        if ids:
            emit_event(
                "task_reassign",
                worker=owner_id,
                count=len(ids),
                penalized=True,
                error=err_message[:200],
            )
        if ids and not failed:
            logger.warning(
                "Re-queueing %d failed tasks of owner %d (%s)",
                len(ids),
                owner_id,
                err_message,
            )

    def _drop_copy_if_twinned_locked(self, tid):
        """A popped in-flight task copy turned out to be half of a backup
        twin pair. Break the links; True when the OTHER copy is still in
        flight (so this one is simply dropped, not requeued)."""
        twin_id = self._twins.pop(tid, None)
        if twin_id is None:
            return False
        self._twins.pop(twin_id, None)
        self._backup_ids.discard(tid)
        _BACKUPS.labels(outcome="copy_recovered").inc()
        emit_event(
            "backup_task", task_id=tid, twin=twin_id,
            phase="copy_recovered",
        )
        return twin_id in self._doing

    def _abandon_locked(self, task, task_id, worker_id, err_message):
        """A task's retry ladder is exhausted: count it LOUDLY (elasticity
        event + counter + job-status field) and fail the job. A silently
        vanishing task is the one failure mode a monitor can never
        distinguish from slow progress."""
        self._tasks_abandoned += 1
        self._job_failed = True
        self._j({
            "op": "abandoned",
            "task_id": task_id,
            "job_failed": True,
        })
        _ABANDONED.inc()
        emit_event(
            "task_abandoned",
            task_id=task_id,
            worker=worker_id,
            shard=task.shard_name,
            start=task.start,
            end=task.end,
            retries=task.retry_count,
            error=err_message[:200],
        )

    def recover_tasks(self, worker_id):
        """Re-queue every in-flight task owned by a dead worker (reference
        task_dispatcher.py:365-377). Called by the instance manager on pod
        failure and by the timeout watchdog."""
        with self._lock:
            ids = [
                tid
                for tid, (wid, _, _) in self._doing.items()
                if wid == worker_id
            ]
            requeued = 0
            recovered_ids, recovered_tasks = [], []
            for tid in ids:
                _, task, _ = self._doing.pop(tid)
                self._lease_tokens.pop(tid, None)
                if self._drop_copy_if_twinned_locked(tid):
                    # A copy of a still-racing twin dies with its worker:
                    # the surviving copy owns the work, nothing to requeue.
                    self._j({"op": "dropped", "task_id": tid})
                    continue
                if self._stop_training and task.type == pb.TRAINING:
                    self._j({"op": "dropped", "task_id": tid})
                    continue
                self._todo.appendleft(task)
                requeued += 1
                recovered_ids.append(tid)
                recovered_tasks.append(_task_to_tuple(task))
            if recovered_ids:
                self._j({
                    "op": "recovered",
                    "worker": worker_id,
                    "task_ids": recovered_ids,
                    "tasks": recovered_tasks,
                })
            self._tasks_recovered += requeued
            self._gauges_locked()
        if requeued:
            _RECOVERED.inc(requeued)
            emit_event(
                "task_reassign",
                worker=worker_id,
                count=requeued,
                task_ids=ids[:32],
            )
            logger.info(
                "Recovered %d tasks from worker %d", requeued, worker_id
            )

    # ---------- status ----------

    def _finished_locked(self):
        epochs_exhausted = (
            not self._training_shards
            or self._epoch >= self._num_epochs
            or self._stop_training
        )
        done = (not self._todo) and (not self._doing) and epochs_exhausted
        if done and self._train_end_pending and not self._job_failed:
            # All training/eval work drained: NOW dispatch the armed
            # train-end task (model export) and report not-finished until a
            # worker completes it.
            self._train_end_pending = False
            name = next(iter(self._training_shards))
            task = _Task(name, 0, 0, pb.TRAIN_END_CALLBACK)
            self._todo.append(task)
            self._j({
                "op": "train_end_consumed",
                "task": _task_to_tuple(task),
            })
            logger.info("Dispatching train-end callback task")
            return False
        return done

    def training_exhausted(self):
        """True when no TRAINING task exists or can ever appear again (todo
        and doing are training-free and the epochs are spent). Once true it
        stays true: new training tasks come only from epoch rollover or
        from requeueing in-flight ones. The lease loop exits on this rather
        than on finished(), which stays False while evaluation/train-end
        work remains."""
        with self._lock:
            if any(t.type == pb.TRAINING for t in self._todo):
                return False
            if any(
                task.type == pb.TRAINING
                for (_, task, _) in self._doing.values()
            ):
                return False
            return (
                not self._training_shards
                or self._epoch >= self._num_epochs
                or self._stop_training
            )

    def finished(self):
        # NB: after stop_training() this still waits for in-flight tasks and
        # queued evaluation tasks to drain (_finished_locked treats the
        # remaining epochs as exhausted) so final evals are not orphaned.
        with self._lock:
            return self._finished_locked()

    @property
    def job_failed(self):
        return self._job_failed

    def stop_training(self):
        """Early-stop hook (max-steps / callback driven, reference
        task_dispatcher.py:134-141)."""
        with self._lock:
            self._stop_training = True
            self._todo = collections.deque(
                t for t in self._todo if t.type != pb.TRAINING
            )
            self._j({
                "op": "stop_training",
                "training_type": int(pb.TRAINING),
            })

    def doing_tasks_over_timeout(self, factor=3.0, min_samples=5):
        """Worker ids whose in-flight task has run > factor x the rolling mean
        completion time for its type (reference master/master.py:487-509)."""
        now = time.time()
        with self._lock:
            slow_workers = set()
            for tid, (wid, task, start) in self._doing.items():
                durations = self._task_durations.get(task.type, [])
                if len(durations) < min_samples:
                    continue
                mean = sum(durations) / len(durations)
                if now - start > factor * max(mean, 1e-3):
                    slow_workers.add(wid)
            return slow_workers

    def add_evaluation_complete_callback(self, cb):
        self._eval_complete_callbacks.append(cb)

    def add_tasks_done_callback(self, cb):
        self._tasks_done_callbacks.append(cb)

    def counts(self):
        stats = self.stats()
        return {"todo": stats["todo"], "doing": stats["doing"]}

    def stats(self):
        """Telemetry snapshot for monitors / the metrics service."""
        with self._lock:
            doing_by_worker = {}
            for wid, _, _ in self._doing.values():
                doing_by_worker[wid] = doing_by_worker.get(wid, 0) + 1
            now = time.time()
            blacklisted = sorted(
                wid for wid in list(self._blacklist)
                if self._blacklisted_locked(wid, now)
            )
            return {
                "todo": len(self._todo),
                "doing": len(self._doing),
                "doing_by_worker": doing_by_worker,
                "epoch": self._epoch,
                "num_epochs": self._num_epochs,
                "epoch_records": sum(
                    n for _, n in self._training_shards.values()
                ),
                "records_done": self._records_done,
                "tasks_recovered": self._tasks_recovered,
                "tasks_abandoned": self._tasks_abandoned,
                "job_failed": self._job_failed,
                "blacklisted": blacklisted,
                "backups_inflight": len(self._backup_ids),
                "backups_launched": self._backups_launched,
                "backup_wins": self._backup_wins,
            }
