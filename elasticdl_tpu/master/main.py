"""`python -m elasticdl_tpu.master.main` — master process entrypoint
(reference /root/reference/elasticdl/python/master/main.py)."""

import sys

from elasticdl_tpu.common.args import master_parser, validate_args
from elasticdl_tpu.master.master import Master


def main(argv=None):
    args = master_parser().parse_args(argv)
    validate_args(args)
    master = Master(args)
    master.prepare()
    return master.run()


if __name__ == "__main__":
    sys.exit(main())
