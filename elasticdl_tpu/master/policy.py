"""Self-healing policy engine: the control loop that ACTS on telemetry.

PRs 3/6 gave the master detection (straggler scores, PS load skew, drain
ETA, alert rules) and PR 9 made the actuator nearly free (warm regroup
0.087 s); this module closes the loop. A master-side control thread reads
the telemetry aggregator's derived summary every tick and turns signals
into actions through three actuators:

- straggler mitigation: a worker whose straggler_score stays above the
  threshold is blacklisted in the task dispatcher (no new tasks route to
  it), its in-flight tasks recover, and the instance manager restarts it
  (the restart is "forgiven" — deliberate mitigation never consumes the
  max_relaunches failure budget).
- speculative backup tasks: the slowest-percentile in-flight tasks get a
  second copy on a healthy worker; first result wins, the loser's late
  report is acknowledged-but-discarded, records_done counts exactly once
  (the dispatcher owns the twin accounting).
- drain-ETA scaling: when the task-queue ETA diverges from
  ELASTICDL_JOB_DEADLINE_SECONDS, the instance manager is asked for ±k
  workers — ANNOUNCED first through the world-hint board so every
  worker's AOT speculator compiles the announced next world instead of
  guessing N±delta (the regroup that follows consumes a prebuilt
  executable).

Every decision — applied, dry-run, or suppressed — lands as a
`policy_decision` event in events.jsonl and increments
`edl_policy_actions_total{action,outcome}`; `edl dash`/`edl top` render
the recent-decision trail. Flap control is layered: per-(rule, subject)
hysteresis (a condition must hold for N consecutive ticks), per-(action,
subject) cooldowns, and a global applied-actions rate limit per sliding
window. A healthy fleet produces ZERO decisions (the no-flap property
the fleet harness tests at 200+ simulated pods).

The engine is detection-framework-agnostic: inputs are the aggregator's
summary() dict plus duck-typed dispatcher / instance-manager actuators,
so the fleet harness embeds it against simulated pods unchanged.
docs/POLICY.md carries the rule catalog and tuning guide.
"""

import collections
import re
import threading
import time

from elasticdl_tpu.chaos import injection
from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import emit_event
from elasticdl_tpu.observability.metrics import default_registry

logger = get_logger("master.policy")

_REG = default_registry()
_ACTIONS = _REG.counter(
    "edl_policy_actions_total",
    "Policy-engine decisions, by action and outcome",
    labelnames=("action", "outcome"),
)

_RATE_WINDOW_S = 60.0
_WORKER_ROLE = re.compile(r"^worker-(\d+)$")


def policy_enabled():
    """ELASTICDL_POLICY truthiness (opt-in: unset means detection-only)."""
    return knobs.get_str("ELASTICDL_POLICY").lower() in (
        "1", "true", "on", "yes",
    )


def _truthy(name):
    return knobs.get_str(name).lower() in ("1", "true", "on", "yes")


class WorldHintBoard:
    """The master-driven half of the world-hint RPC: the policy engine
    announces the target worker world BEFORE actuating a scale event;
    workers poll get_world_hint and speculatively compile the announced
    world. hint_seq is monotonic; 0 means nothing was ever announced.

    The seq survives master restarts: a journal-recovered board resumes
    from the replayed seq (restore_state) and every announce is journaled
    — a board restarting at 0 would make trainers silently ignore every
    post-restart hint as stale."""

    def __init__(self, time_fn=time.time):
        self._lock = threading.Lock()
        self._time = time_fn
        self._seq = 0
        self._target = 0
        self._reason = ""
        self._ts = 0.0
        self._journal = None

    def attach_journal(self, journal):
        with self._lock:
            self._journal = journal

    def restore_state(self, state):
        """Resume from a replayed journal state (hint_seq monotonicity
        across incarnations)."""
        with self._lock:
            self._seq = max(self._seq, int(state.get("hint_seq", 0)))
            self._target = int(state.get("hint_target", 0))
            self._reason = str(state.get("hint_reason", ""))
            if self._seq:
                self._ts = self._time()

    def export_state(self):
        with self._lock:
            return {
                "hint_seq": self._seq,
                "hint_target": self._target,
                "hint_reason": self._reason,
            }

    def announce(self, target_world_size, reason=""):
        with self._lock:
            self._seq += 1
            self._target = int(target_world_size)
            self._reason = reason
            self._ts = self._time()
            seq = self._seq
            if self._journal is not None:
                # Write-ahead: the hint is durable BEFORE any worker can
                # observe it, so a crash between announce and actuation
                # cannot regress hint_seq on recovery.
                self._journal.record({
                    "op": "hint",
                    "seq": seq,
                    "target": int(target_world_size),
                    "reason": reason[:200],
                })
        emit_event(
            "world_hint",
            # Named hint_seq, NOT seq: the event envelope stamps its own
            # `seq` (file order) over the payload, which would silently
            # shadow the hint's sequence number.
            hint_seq=seq,
            target_world_size=int(target_world_size),
            reason=reason[:200],
        )
        logger.info(
            "World hint #%d: target world %d (%s)",
            seq, target_world_size, reason,
        )
        return seq

    def current(self):
        """Dict snapshot mirroring pb.WorldHintResponse."""
        with self._lock:
            return {
                "hint_seq": self._seq,
                "target_world_size": self._target,
                "reason": self._reason,
                "age_seconds": (
                    0.0 if not self._seq else self._time() - self._ts
                ),
            }


class PolicyEngine:
    """Hysteresis/cooldown/rate-limited rule evaluator over the
    aggregator summary, actuating through the dispatcher, the instance
    manager, and the world-hint board."""

    def __init__(
        self,
        summary_fn,
        dispatcher,
        instance_manager=None,
        world_hints=None,
        interval=None,
        dry_run=None,
        hysteresis=None,
        cooldown_seconds=None,
        rate_limit=None,
        deadline_seconds=None,
        time_fn=time.time,
    ):
        self._summary_fn = summary_fn
        self._dispatcher = dispatcher
        self._instance_manager = instance_manager
        self._world_hints = world_hints
        self._time = time_fn

        self._interval = (
            knobs.get_float("ELASTICDL_POLICY_INTERVAL")
            if interval is None else interval
        )
        self._dry_run = (
            _truthy("ELASTICDL_POLICY_DRY_RUN")
            if dry_run is None else dry_run
        )
        self._hysteresis = max(1, (
            knobs.get_int("ELASTICDL_POLICY_HYSTERESIS")
            if hysteresis is None else hysteresis
        ))
        self._cooldown_s = (
            knobs.get_float("ELASTICDL_POLICY_COOLDOWN_SECONDS")
            if cooldown_seconds is None else cooldown_seconds
        )
        self._rate_limit = (
            knobs.get_int("ELASTICDL_POLICY_RATE_LIMIT")
            if rate_limit is None else rate_limit
        )
        self._deadline_s = (
            knobs.get_float("ELASTICDL_JOB_DEADLINE_SECONDS")
            if deadline_seconds is None else deadline_seconds
        )
        self._straggler_score = knobs.get_float(
            "ELASTICDL_POLICY_STRAGGLER_SCORE"
        )
        self._blacklist_s = knobs.get_float(
            "ELASTICDL_POLICY_BLACKLIST_SECONDS"
        )
        self._max_backups = knobs.get_int("ELASTICDL_POLICY_MAX_BACKUPS")
        self._backup_factor = knobs.get_float(
            "ELASTICDL_POLICY_BACKUP_FACTOR"
        )
        self._scale_step = max(
            1, knobs.get_int("ELASTICDL_POLICY_SCALE_STEP")
        )
        self._max_workers = knobs.get_int("ELASTICDL_POLICY_MAX_WORKERS")

        self._job_start = self._time()
        self._initial_workers = None
        if instance_manager is not None:
            try:
                self._initial_workers = instance_manager.worker_count()
            except Exception:
                self._initial_workers = None

        self._counters = {}  # (rule, subject) -> consecutive trigger ticks
        self._cooldowns = {}  # (action, subject) -> last applied ts
        self._journal = None  # applied decisions journal their cooldowns
        self._applied_window = collections.deque()  # applied-action stamps
        self._recent = collections.deque(maxlen=64)  # decision dicts
        self._actions_total = 0  # APPLIED actions only
        self._ticks = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    # ---------- journal plane ----------

    def attach_journal(self, journal):
        self._journal = journal

    def restore_state(self, state):
        """Resume without re-firing decisions already applied: the
        journaled (action, subject) -> ts cooldown map is restored, so a
        decision applied just before the crash stays in cooldown after
        the relaunch instead of firing again."""
        cooldowns = {}
        for key, ts in (state.get("cooldowns") or {}).items():
            action, _, subject = key.partition("|")
            cooldowns[(action, subject)] = float(ts)
        with self._lock:
            self._cooldowns.update(cooldowns)

    def export_state(self):
        with self._lock:
            return {
                "cooldowns": {
                    f"{action}|{subject}": ts
                    for (action, subject), ts in self._cooldowns.items()
                },
            }

    # ---------- lifecycle ----------

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="policy-engine"
        )
        self._thread.start()
        logger.info(
            "Policy engine started (interval=%.1fs dry_run=%s "
            "hysteresis=%d cooldown=%.0fs rate_limit=%d/min)",
            self._interval, self._dry_run, self._hysteresis,
            self._cooldown_s, self._rate_limit,
        )
        return self

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                logger.exception("Policy tick failed (loop continues)")
            self._stop.wait(self._interval)

    # ---------- evaluation ----------

    def tick(self, now=None):
        """Evaluate every rule once; returns the decisions made this
        tick (empty on a healthy fleet — the no-flap property)."""
        now = self._time() if now is None else now
        summary = self._summary_fn() or {}
        decisions = []
        decisions += self._rule_straggler(summary, now)
        decisions += self._rule_backup(summary, now)
        decisions += self._rule_deadline(summary, now)
        with self._lock:
            self._ticks += 1
        return decisions

    def _hold(self, rule, subject, triggered):
        """Per-(rule, subject) hysteresis: True once the condition held
        for the configured number of CONSECUTIVE ticks."""
        key = (rule, subject)
        if not triggered:
            self._counters.pop(key, None)
            return False
        count = self._counters.get(key, 0) + 1
        self._counters[key] = count
        return count >= self._hysteresis

    def _prune_counters(self, rule, live_subjects):
        """Drop hysteresis state for subjects that left the signal set
        (completed tasks, scaled-away workers)."""
        for key in list(self._counters):
            if key[0] == rule and key[1] not in live_subjects:
                del self._counters[key]

    def _decide(self, action, subject, reason, actuate, now,
                rule_key=None):
        """Run one decision through dry-run -> cooldown -> rate limit ->
        actuation; always emits the policy_decision event + counter."""
        cd_key = (action, subject)
        if self._dry_run:
            outcome = "dry_run"
        elif (
            self._cooldown_s > 0
            and now - self._cooldowns.get(cd_key, -1e18) < self._cooldown_s
        ):
            outcome = "cooldown"
        elif self._rate_limit > 0 and not self._admit_rate(now):
            outcome = "rate_limited"
        else:
            try:
                actuate()
                outcome = "applied"
                with self._lock:
                    self._cooldowns[cd_key] = now
                if self._journal is not None:
                    self._journal.record({
                        "op": "cooldown",
                        "key": f"{action}|{subject}",
                        "ts": now,
                    })
                self._applied_window.append(now)
                with self._lock:
                    self._actions_total += 1
            except Exception as exc:
                logger.exception("Policy action %s(%s) failed",
                                 action, subject)
                outcome = "error"
                reason = f"{reason}; error={exc!r}"
        # Any decision (applied or suppressed) restarts the hysteresis
        # window, so a suppressed rule re-earns its trigger instead of
        # spamming one decision per tick.
        if rule_key is not None:
            self._counters.pop(rule_key, None)
        _ACTIONS.labels(action=action, outcome=outcome).inc()
        decision = {
            "ts": round(now, 3),
            "action": action,
            "subject": str(subject),
            "outcome": outcome,
            "reason": reason,
        }
        emit_event(
            "policy_decision",
            action=action,
            subject=str(subject),
            outcome=outcome,
            reason=reason[:200],
        )
        logger.info(
            "Policy decision: %s(%s) -> %s (%s)",
            action, subject, outcome, reason,
        )
        with self._lock:
            self._recent.append(decision)
        return decision

    def _admit_rate(self, now):
        while (
            self._applied_window
            and now - self._applied_window[0] > _RATE_WINDOW_S
        ):
            self._applied_window.popleft()
        return len(self._applied_window) < self._rate_limit

    # ---------- rules ----------

    def _rule_straggler(self, summary, now):
        """Persistent straggler -> blacklist + recover tasks + restart."""
        workers = summary.get("workers") or {}
        blacklisted = set(self._dispatcher.blacklisted_workers())
        decisions = []
        live = set()
        for role in sorted(workers):
            match = _WORKER_ROLE.match(role)
            if not match:
                continue
            wid = int(match.group(1))
            live.add(role)
            score = workers[role].get("straggler_score") or 0.0
            triggered = (
                score >= self._straggler_score
                and wid not in blacklisted
            )
            if not self._hold("straggler", role, triggered):
                continue
            reason = (
                f"straggler_score={score:.2f} >= "
                f"{self._straggler_score:.2f} for "
                f"{self._hysteresis} ticks"
            )
            decisions.append(self._decide(
                "straggler_blacklist", role, reason,
                lambda wid=wid, reason=reason: self._mitigate_straggler(
                    wid, reason
                ),
                now,
                rule_key=("straggler", role),
            ))
        self._prune_counters("straggler", live)
        return decisions

    def _mitigate_straggler(self, wid, reason):
        self._dispatcher.blacklist_worker(wid, self._blacklist_s, reason)
        # Its in-flight tasks re-dispatch to healthy workers immediately;
        # the restart (when an instance manager exists) gives the slot a
        # fresh process that rehydrates from the compile cache.
        self._dispatcher.recover_tasks(wid)
        if self._instance_manager is not None:
            self._instance_manager.restart_worker(wid, reason)

    def _rule_backup(self, summary, now):
        """Slowest-percentile in-flight tasks -> speculative copy."""
        if self._max_backups <= 0:
            return []
        stats = self._dispatcher.stats()
        budget = self._max_backups - stats.get("backups_inflight", 0)
        if budget <= 0:
            self._prune_counters("backup", set())
            return []
        candidates = self._dispatcher.backup_candidates(
            factor=self._backup_factor, limit=budget
        )
        decisions = []
        live = set()
        for tid, wid, elapsed in candidates:
            live.add(tid)
            if not self._hold("backup", tid, True):
                continue
            reason = (
                f"task {tid} on worker {wid} in flight "
                f"{elapsed:.1f}s (> {self._backup_factor:.1f}x mean)"
            )
            decisions.append(self._decide(
                "backup_task", f"task-{tid}", reason,
                lambda tid=tid: self._dispatcher.request_backup(tid),
                now,
                rule_key=("backup", tid),
            ))
        self._prune_counters("backup", live)
        return decisions

    def _job_eta(self, summary):
        """Whole-job drain ETA in seconds, or None while unmeasurable.

        The aggregator's eta_seconds gauge is EPOCH-scoped: the
        dispatcher regenerates training tasks lazily per epoch, so its
        todo queue — and any ETA built on it — only ever sees the
        current epoch's tail. A deadline rule fed that number would
        declare a 400-epoch job "nearly done" from epoch 1. Compute the
        job-wide ETA from total planned records instead, and fall back
        to the queue-scoped ETA for jobs without a records plan
        (evaluation-only)."""
        stats = self._dispatcher.stats()
        epoch_records = stats.get("epoch_records") or 0
        total = epoch_records * stats.get("num_epochs", 0)
        rps = summary.get("records_per_second")
        if total > 0 and rps:
            return max(0.0, total - stats.get("records_done", 0)) / rps
        return (summary.get("tasks") or {}).get("eta_seconds")

    def _rule_deadline(self, summary, now):
        """Drain ETA vs. deadline -> announce the next world, then ±k."""
        if self._deadline_s <= 0 or self._instance_manager is None:
            return []
        eta = self._job_eta(summary)
        if eta is None:
            self._prune_counters("scale_up", set())
            self._prune_counters("scale_down", set())
            return []
        remaining = self._deadline_s - (now - self._job_start)
        n = self._instance_manager.worker_count()
        initial = self._initial_workers or n or 1
        max_workers = self._max_workers or 2 * initial
        k = self._scale_step
        behind = eta > 1.2 * max(remaining, 1.0)
        ahead = remaining > 0 and eta < 0.5 * remaining
        decisions = []
        if self._hold("scale_up", "fleet", behind and n + k <= max_workers):
            reason = (
                f"eta={eta:.0f}s overshoots remaining="
                f"{remaining:.0f}s; {n} -> {n + k} workers"
            )
            decisions.append(self._decide(
                "scale_up", "fleet", reason,
                lambda n=n, reason=reason: self._scale(k, n + k, reason),
                now,
                rule_key=("scale_up", "fleet"),
            ))
        if self._hold("scale_down", "fleet", ahead and n - k >= initial):
            reason = (
                f"eta={eta:.0f}s well under remaining="
                f"{remaining:.0f}s; {n} -> {n - k} workers"
            )
            decisions.append(self._decide(
                "scale_down", "fleet", reason,
                lambda n=n, reason=reason: self._scale(-k, n - k, reason),
                now,
                rule_key=("scale_down", "fleet"),
            ))
        return decisions

    def _scale(self, delta, target_world, reason):
        # Announce FIRST: workers poll the hint and speculatively compile
        # the announced world while the instance manager actuates, so the
        # regroup consumes a prebuilt executable (aot_consumed).
        if self._world_hints is not None:
            self._world_hints.announce(target_world, reason)
        # Chaos seam for the master-kill-during-scale drill: the hint is
        # journaled/announced but the actuation below never happens.
        injection.inject_local("master.scale")
        self._instance_manager.scale_workers(delta, reason)

    # ---------- status ----------

    def actions_total(self):
        with self._lock:
            return self._actions_total

    def summary(self):
        """JSON-able policy section for /api/summary and `edl dash`."""
        stats = self._dispatcher.stats()
        with self._lock:
            recent = list(self._recent)[-8:]
            total = self._actions_total
            ticks = self._ticks
        out = {
            "enabled": True,
            "dry_run": self._dry_run,
            "interval_s": self._interval,
            "ticks": ticks,
            "actions_total": total,
            "recent": recent,
            "blacklisted": [
                f"worker-{wid}" for wid in stats.get("blacklisted", [])
            ],
            "backups_inflight": stats.get("backups_inflight", 0),
            "backups_launched": stats.get("backups_launched", 0),
            "backup_wins": stats.get("backup_wins", 0),
        }
        if self._world_hints is not None:
            hint = self._world_hints.current()
            if hint["hint_seq"]:
                out["world_hint"] = {
                    "seq": hint["hint_seq"],
                    "target_world_size": hint["target_world_size"],
                    "reason": hint["reason"],
                    "age_seconds": round(hint["age_seconds"], 1),
                }
        return out
