"""Step-synchronized task leases: dynamic data sharding for SPMD worlds.

The reference's AllReduce workers pull tasks independently because Horovod
tolerates ragged per-worker step counts
(/root/reference/elasticdl/python/worker/allreduce_trainer.py:39-184). A
jax.distributed SPMD world cannot: every process executes the same compiled
program the same number of times, or the collectives deadlock. This manager
reconciles dynamic sharding with that constraint by leasing work to the
WHOLE world at once:

- A lease pops TRAINING tasks from the dispatcher (attributed to a
  synthetic owner id), splits their record space into contiguous per-rank
  sub-ranges, and fixes one shared `n_steps` — every rank runs exactly
  n_steps minibatches, cycling its own records to fill the tail.
- The underlying tasks complete only when EVERY rank of the lease's world
  reports success; a failure report or a membership-epoch bump aborts the
  lease and requeues its tasks (`TaskDispatcher.recover_tasks` on the
  synthetic owner), exactly like a dead worker's tasks recover in the
  reference (task_dispatcher.py:365-377). Re-running a partially-trained
  lease matches the reference's semantics for interrupted tasks.

Epoch observation is lazy: every lease_steps/report_lease call compares the
membership's current group_id with the active lease's epoch — no extra
threads, no callbacks.
"""

import threading

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import emit_event
from elasticdl_tpu.observability.metrics import default_registry
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

logger = get_logger("master.step_lease")

_LEASES = default_registry().counter(
    "edl_leases_total",
    "Step-lease lifecycle transitions",
    labelnames=("event",),
)

# Dispatcher owner ids for leases live far below real worker ids so the
# watchdog/instance-manager recovery paths can tell them apart.
_OWNER_BASE = -1000


def lease_owner_id(lease_id):
    return _OWNER_BASE - lease_id


def is_lease_owner(worker_id):
    return worker_id <= _OWNER_BASE


class _Lease:
    def __init__(self, lease_id, epoch, world, batch_size):
        self.id = lease_id
        self.epoch = epoch
        self.world = world
        self.batch_size = batch_size
        self.n_steps = 0
        self.rank_ranges = [[] for _ in range(world)]
        self.task_ids = []
        self.reported = set()  # ranks that reported success


class StepLeaseManager:
    def __init__(self, task_dispatcher, membership, target_steps=8):
        """target_steps: aim for this many steps per lease per rank — the
        granularity of elasticity (membership changes apply at lease
        boundaries, or mid-lease via collective failure)."""
        self._task_d = task_dispatcher
        self._membership = membership
        self._target_steps = max(1, target_steps)
        self._lock = threading.Lock()
        self._active = None
        self._next_lease_id = 1

    # ---------- RPC entry points ----------

    def lease_steps(self, worker_id, worker_host, batch_size):
        """Returns a pb.LeaseStepsResponse for this worker."""
        rank, world, epoch, _, _ = self._membership.get_comm_rank(
            worker_host
        )
        with self._lock:
            self._abort_if_stale_locked(epoch)
            if rank < 0 or world <= 0:
                # Not registered in the group yet: the caller registers via
                # report_worker_liveness and retries.
                return pb.LeaseStepsResponse(
                    status=pb.LeaseStepsResponse.WAIT
                )
            if self._active is None:
                self._mint_locked(epoch, world, max(1, batch_size))
            if self._active is None:
                # FINISHED only when no training work can ever reappear;
                # evaluation/train-end tasks drain through the regular
                # task loop after the lease loop exits.
                status = (
                    pb.LeaseStepsResponse.FINISHED
                    if self._task_d.training_exhausted()
                    else pb.LeaseStepsResponse.WAIT
                )
                return pb.LeaseStepsResponse(status=status)
            lease = self._active
            if rank in lease.reported:
                # This rank already ran the active lease; peers are still
                # working. Handing the same lease back would double-run it.
                return pb.LeaseStepsResponse(
                    status=pb.LeaseStepsResponse.WAIT
                )
            res = pb.LeaseStepsResponse(
                status=pb.LeaseStepsResponse.OK,
                lease_id=lease.id,
                epoch=lease.epoch,
                rank=rank,
                world_size=lease.world,
                n_steps=lease.n_steps,
            )
            for shard, start, end in lease.rank_ranges[rank]:
                res.ranges.append(
                    pb.LeaseRange(shard_name=shard, start=start, end=end)
                )
            _LEASES.labels(event="grant").inc()
            emit_event(
                "lease_grant",
                lease_id=lease.id,
                epoch=lease.epoch,
                rank=rank,
                worker=worker_id,
                n_steps=lease.n_steps,
            )
            return res

    def report_lease(self, lease_id, rank, success, err_message=""):
        complete = False
        with self._lock:
            self._abort_if_stale_locked(self._membership.group_id)
            lease = self._active
            if lease is None or lease.id != lease_id:
                # A stale report for an aborted/completed lease: its tasks
                # were already requeued (or completed); nothing to do.
                logger.info(
                    "Ignoring report for non-active lease %d (rank %d)",
                    lease_id,
                    rank,
                )
                return
            _LEASES.labels(event="report").inc()
            emit_event(
                "lease_report",
                lease_id=lease_id,
                rank=rank,
                success=success,
            )
            if not success:
                logger.warning(
                    "Lease %d failed on rank %d (%s); requeueing its tasks",
                    lease_id,
                    rank,
                    err_message,
                )
                # Fault-attributed abort: tasks pass through the retry
                # ladder so a deterministic failure (corrupt range, bad
                # feed) fails the job after max retries instead of
                # re-minting the same doomed lease forever. Epoch-change
                # aborts stay free (a worker death is not the data's
                # fault).
                self._abort_locked(penalize=True, err_message=err_message)
                return
            lease.reported.add(rank)
            if len(lease.reported) >= lease.world:
                for tid in lease.task_ids:
                    self._task_d.report(tid, True)
                logger.info(
                    "Lease %d complete (%d ranks, %d tasks)",
                    lease.id,
                    lease.world,
                    len(lease.task_ids),
                )
                _LEASES.labels(event="complete").inc()
                emit_event(
                    "lease_complete",
                    lease_id=lease.id,
                    world=lease.world,
                    tasks=len(lease.task_ids),
                )
                self._active = None
                complete = True
        return complete

    # ---------- internals ----------

    def _abort_if_stale_locked(self, epoch):
        if self._active is not None and self._active.epoch != epoch:
            logger.info(
                "Membership epoch %d != lease epoch %d; aborting lease %d",
                epoch,
                self._active.epoch,
                self._active.id,
            )
            self._abort_locked()

    def _abort_locked(self, penalize=False, err_message=""):
        lease = self._active
        self._active = None
        if lease is None:
            return
        _LEASES.labels(event="abort").inc()
        emit_event(
            "lease_abort",
            lease_id=lease.id,
            epoch=lease.epoch,
            penalized=penalize,
            error=err_message[:200],
        )
        owner = lease_owner_id(lease.id)
        if penalize:
            self._task_d.fail_owner_tasks(owner, err_message)
        else:
            self._task_d.recover_tasks(owner)

    def _mint_locked(self, epoch, world, batch_size):
        """Pop training tasks covering ~target_steps * world * batch
        records and split them into per-rank contiguous sub-ranges."""
        lease_id = self._next_lease_id
        owner = lease_owner_id(lease_id)
        want = self._target_steps * world * batch_size
        tasks = []  # (task_id, _Task)
        got = 0
        while got < want:
            task_id, task = self._task_d.get_typed(owner, pb.TRAINING)
            if task is None:
                break
            tasks.append((task_id, task))
            got += task.end - task.start
        if not tasks:
            return
        self._next_lease_id += 1
        lease = _Lease(lease_id, epoch, world, batch_size)
        lease.task_ids = [tid for tid, _ in tasks]

        # Split the concatenated record space into `world` contiguous
        # chunks (first `extra` ranks get one more record).
        base, extra = divmod(got, world)
        quotas = [base + (1 if r < extra else 0) for r in range(world)]
        rank = 0
        for _, task in tasks:
            pos = task.start
            while pos < task.end:
                while rank < world and quotas[rank] == 0:
                    rank += 1
                if rank >= world:  # only when got < world left ranks empty
                    break
                take = min(task.end - pos, quotas[rank])
                lease.rank_ranges[rank].append(
                    (task.shard_name, pos, pos + take)
                )
                quotas[rank] -= take
                pos += take
        # Fewer records than ranks: empty ranks re-train the head of the
        # lease (cyclic duplication — the same reweighting the batch
        # padder applies, so every rank still holds real data).
        first = lease.rank_ranges[0] or [
            (tasks[0][1].shard_name, tasks[0][1].start,
             tasks[0][1].start + 1)
        ]
        for r in range(world):
            if not lease.rank_ranges[r]:
                lease.rank_ranges[r] = [first[0]]
        per_rank = max(
            sum(e - s for _, s, e in ranges)
            for ranges in lease.rank_ranges
        )
        lease.n_steps = max(1, -(-per_rank // batch_size))
        self._active = lease
        _LEASES.labels(event="mint").inc()
        emit_event(
            "lease_mint",
            lease_id=lease.id,
            epoch=epoch,
            world=world,
            tasks=len(tasks),
            records=got,
            n_steps=lease.n_steps,
        )
        logger.info(
            "Minted lease %d: epoch %d, world %d, %d tasks (%d records), "
            "%d steps x batch %d per rank",
            lease.id,
            epoch,
            world,
            len(tasks),
            got,
            lease.n_steps,
            batch_size,
        )
