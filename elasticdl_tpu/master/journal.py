"""Write-ahead journal + snapshot plane for job-critical master state.

The master is the one component whose death used to kill the job
unrecoverably: the task queue, records_done accounting, membership epoch,
world-hint seq, and policy cooldowns all lived only in process memory.
This module externalizes that state so a relaunched master replays it and
re-enters the job with a bumped incarnation.

Stdlib only — no jax, no grpc, no proto imports — so the unit surface
(tests/test_journal.py) runs in milliseconds and the module can be lifted
into a future sharded-dispatcher process unchanged.

On-disk layout (under ELASTICDL_MASTER_JOURNAL_DIR):

    snapshot.json       last compacted full state (atomic os.replace)
    snapshot.json.tmp   torn snapshot litter — ignored at load (the
                        previous snapshot stays authoritative, mirroring
                        the PR 2 torn-checkpoint rules)
    wal.log             CRC-framed append records SINCE the snapshot

WAL framing, per record:

    [4-byte LE payload length][4-byte LE zlib.crc32][payload JSON bytes]

Read rules: an *incomplete* frame at EOF is a torn tail from a crash
mid-append — silently dropped, never poisons replay. A *complete* frame
whose CRC mismatches is real corruption mid-file — fails loudly
(JournalCorruptError) because silently skipping it would desync the
replayed state machine from the acked RPC history.

Write-ahead ordering contract: every mutating op is appended (and fsynced
when durable) BEFORE the RPC ack leaves the master.  That is what makes
result reporting exactly-once across a master restart: a `done` journaled
then crashed is replayed, so the worker's retried report hits the
unknown-task discard path; a crash *before* the append leaves the lease
in doing, so the retried report is accepted exactly once.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)

_FRAME_HEADER = struct.Struct("<II")  # payload length, crc32(payload)

SNAPSHOT_NAME = "snapshot.json"
WAL_NAME = "wal.log"


class JournalCorruptError(RuntimeError):
    """A complete mid-file record failed its CRC — replay must not continue."""


def _encode_frame(payload: bytes) -> bytes:
    return _FRAME_HEADER.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload


def read_frames(data: bytes) -> List[dict]:
    """Decode framed records; torn tail dropped, mid-file corruption loud."""
    out: List[dict] = []
    off, n = 0, len(data)
    while off < n:
        if off + _FRAME_HEADER.size > n:
            break  # torn tail: header itself truncated
        length, crc = _FRAME_HEADER.unpack_from(data, off)
        start = off + _FRAME_HEADER.size
        end = start + length
        if end > n:
            break  # torn tail: payload truncated by the crash
        payload = data[start:end]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise JournalCorruptError(
                "journal record at offset %d failed CRC (len=%d)" % (off, length)
            )
        out.append(json.loads(payload.decode("utf-8")))
        off = end
    return out


class Journal:
    """Low-level framed WAL + atomic snapshot pair in one directory."""

    def __init__(self, directory: str, durable: bool = True):
        self._dir = directory
        self._durable = durable
        os.makedirs(directory, exist_ok=True)
        self._snapshot_path = os.path.join(directory, SNAPSHOT_NAME)
        self._wal_path = os.path.join(directory, WAL_NAME)
        self._lock = threading.Lock()
        self._wal_f = open(self._wal_path, "ab")

    # -- read side ---------------------------------------------------------

    def load(self) -> Tuple[Optional[dict], List[dict]]:
        """Return (snapshot_state_or_None, wal_ops). Torn .tmp litter ignored."""
        snapshot = None
        if os.path.exists(self._snapshot_path):
            with open(self._snapshot_path, "rb") as f:
                snapshot = json.loads(f.read().decode("utf-8"))
        with open(self._wal_path, "rb") as f:
            ops = read_frames(f.read())
        return snapshot, ops

    # -- write side --------------------------------------------------------

    def append(self, op: dict) -> None:
        payload = json.dumps(op, separators=(",", ":"), sort_keys=True).encode("utf-8")
        with self._lock:
            self._wal_f.write(_encode_frame(payload))
            self._wal_f.flush()
            if self._durable:
                os.fsync(self._wal_f.fileno())

    def snapshot(self, state: dict) -> None:
        """Atomically replace the snapshot and truncate the WAL (compaction).

        Crash before os.replace leaves `.tmp` litter and the previous
        snapshot + full WAL authoritative; crash after it but before the
        truncate merely replays ops already folded into the snapshot,
        which the replay machine tolerates (ops are keyed by ids that the
        snapshot already consumed — see replay()).  To keep that window
        harmless we truncate FIRST into a fresh WAL handle, then publish.
        """
        payload = json.dumps(state, separators=(",", ":"), sort_keys=True).encode(
            "utf-8"
        )
        tmp = self._snapshot_path + ".tmp"
        with self._lock:
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                if self._durable:
                    os.fsync(f.fileno())
            # Publish the snapshot, then reset the WAL: if we crash between
            # the two, replaying the stale WAL on top of the new snapshot
            # must be idempotent — replay() drops ops whose subjects the
            # snapshot has already retired.
            os.replace(tmp, self._snapshot_path)
            self._wal_f.close()
            self._wal_f = open(self._wal_path, "wb")
            self._wal_f.flush()
            if self._durable:
                os.fsync(self._wal_f.fileno())

    def close(self) -> None:
        with self._lock:
            try:
                self._wal_f.close()
            except Exception:  # noqa: BLE001 - close is best-effort
                pass


# ---------------------------------------------------------------------------
# Replay state machine
# ---------------------------------------------------------------------------
#
# The journaled state is a plain JSON dict with this shape (all keys always
# present after empty_state()):
#
#   incarnation        int   bumped by each master (re)start
#   next_task_id       int   dispatcher allocation cursor
#   next_lease_token   int   monotonic lease-token cursor
#   epoch              int   dispatcher epoch cursor
#   todo               list  of task tuples [shard, start, end, type, mv, retry]
#   doing              dict  task_id(str) -> {worker, task, token}
#   records_done       int
#   tasks_recovered    int
#   tasks_abandoned    int
#   job_failed         bool
#   stop_training      bool
#   train_end_pending  bool
#   done_ids           list  task_ids acked done (retired-lease dedup ring)
#   twins              dict  task_id(str) -> twin task_id
#   backup_ids         list
#   retired_twins      list
#   backups_launched   int
#   backup_wins        int
#   blacklist          dict  worker -> expiry ts (absolute)
#   hint_seq           int   world-hint board cursor
#   hint_target        int
#   hint_reason        str
#   membership_epoch   int
#   cooldowns          dict  "action|subject" -> ts (policy hysteresis)
#   train_end_enabled  bool
#
# Tasks travel the journal as 6-tuples (lists in JSON):
#   [shard_name, start, end, task_type, model_version, retry_count]

TaskTuple = List[Any]

# Retired-lease dedup ring: enough to absorb any realistic in-flight set
# while bounding snapshot size.
_DONE_RING = 4096


def empty_state() -> Dict[str, Any]:
    return {
        "incarnation": 0,
        "next_task_id": 0,
        "next_lease_token": 0,
        "epoch": 0,
        "todo": [],
        "doing": {},
        "records_done": 0,
        "tasks_recovered": 0,
        "tasks_abandoned": 0,
        "job_failed": False,
        "stop_training": False,
        "train_end_pending": False,
        "done_ids": [],
        "twins": {},
        "backup_ids": [],
        "retired_twins": [],
        "backups_launched": 0,
        "backup_wins": 0,
        "blacklist": {},
        "hint_seq": 0,
        "hint_target": 0,
        "hint_reason": "",
        "membership_epoch": 0,
        "cooldowns": {},
        "train_end_enabled": False,
    }


def _trim_ring(state: Dict[str, Any]) -> None:
    if len(state["done_ids"]) > _DONE_RING:
        del state["done_ids"][: len(state["done_ids"]) - _DONE_RING]


def _drop_twin_links(state: Dict[str, Any], tid: str) -> None:
    twin = state["twins"].pop(tid, None)
    if twin is not None:
        state["twins"].pop(str(twin), None)


def apply_op(state: Dict[str, Any], op: Dict[str, Any]) -> None:
    """Fold one journaled op into state. Mechanical — no RNG, no clocks."""
    kind = op["op"]
    if kind == "incarnation":
        state["incarnation"] = max(state["incarnation"], int(op["value"]))
    elif kind == "tasks_created":
        # Epoch roll / eval batch: the op carries the explicit task tuples
        # so replay never re-derives a shuffle from RNG state.
        state["epoch"] = int(op.get("epoch", state["epoch"]))
        tasks = [list(t) for t in op["tasks"]]
        if op.get("at_front"):
            state["todo"][0:0] = tasks
        else:
            state["todo"].extend(tasks)
    elif kind == "lease":
        tid = str(op["task_id"])
        task = list(op["task"])
        # Remove the first matching todo entry (the dispatcher popped it).
        for i, t in enumerate(state["todo"]):
            if t == task:
                del state["todo"][i]
                break
        state["doing"][tid] = {
            "worker": op["worker"],
            "task": task,
            "token": int(op.get("token", 0)),
        }
        state["next_task_id"] = max(state["next_task_id"], int(op["task_id"]) + 1)
        state["next_lease_token"] = max(
            state["next_lease_token"], int(op.get("token", 0))
        )
    elif kind == "backup_lease":
        tid = str(op["task_id"])
        primary = str(op["primary_id"])
        state["doing"][tid] = {
            "worker": op["worker"],
            "task": list(op["task"]),
            "token": int(op.get("token", 0)),
        }
        state["twins"][primary] = int(op["task_id"])
        state["twins"][tid] = int(op["primary_id"])
        if int(op["task_id"]) not in state["backup_ids"]:
            state["backup_ids"].append(int(op["task_id"]))
        state["backups_launched"] += 1
        state["next_task_id"] = max(state["next_task_id"], int(op["task_id"]) + 1)
        state["next_lease_token"] = max(
            state["next_lease_token"], int(op.get("token", 0))
        )
    elif kind == "done":
        tid = str(op["task_id"])
        entry = state["doing"].pop(tid, None)
        if entry is None and tid in map(str, state["done_ids"]):
            return  # idempotent re-apply (stale-WAL-over-new-snapshot window)
        state["done_ids"].append(int(op["task_id"]))
        _trim_ring(state)
        state["records_done"] += int(op.get("records", 0))
        if op.get("backup_win"):
            state["backup_wins"] += 1
        retire = op.get("retire_twin")
        if retire is not None:
            rid = str(retire)
            state["doing"].pop(rid, None)
            if int(retire) not in state["retired_twins"]:
                state["retired_twins"].append(int(retire))
        _drop_twin_links(state, tid)
        if retire is not None:
            state["twins"].pop(str(retire), None)
        if int(op["task_id"]) in state["backup_ids"]:
            state["backup_ids"].remove(int(op["task_id"]))
        if retire is not None and int(retire) in state["backup_ids"]:
            state["backup_ids"].remove(int(retire))
    elif kind == "failed_requeue":
        tid = str(op["task_id"])
        state["doing"].pop(tid, None)
        state["done_ids"].append(int(op["task_id"]))
        _trim_ring(state)
        state["todo"].insert(0, list(op["task"]))
        _drop_twin_links(state, tid)
        if int(op["task_id"]) in state["backup_ids"]:
            state["backup_ids"].remove(int(op["task_id"]))
    elif kind == "abandoned":
        tid = str(op["task_id"])
        state["doing"].pop(tid, None)
        state["done_ids"].append(int(op["task_id"]))
        _trim_ring(state)
        state["tasks_abandoned"] += 1
        if op.get("job_failed"):
            state["job_failed"] = True
            state["todo"] = []
        _drop_twin_links(state, tid)
    elif kind == "recovered":
        # A worker's in-flight leases were requeued (watchdog / explicit).
        for tid, task in zip(op["task_ids"], op["tasks"]):
            entry = state["doing"].pop(str(tid), None)
            if entry is None:
                continue
            state["todo"].insert(0, list(task))
            state["tasks_recovered"] += 1
            _drop_twin_links(state, str(tid))
    elif kind == "dropped":
        # A lease resolved without accounting: failed copy of a racing
        # twin, early-stop discard, or a dead twin copy.
        tid = str(op["task_id"])
        if state["doing"].pop(tid, None) is not None:
            state["done_ids"].append(int(op["task_id"]))
            _trim_ring(state)
        _drop_twin_links(state, tid)
        if int(op["task_id"]) in state["backup_ids"]:
            state["backup_ids"].remove(int(op["task_id"]))
    elif kind == "blacklist":
        state["blacklist"][str(op["worker"])] = [
            float(op["until"]), str(op.get("reason", "")),
        ]
    elif kind == "unblacklist":
        state["blacklist"].pop(str(op["worker"]), None)
    elif kind == "train_end_enabled":
        state["train_end_pending"] = bool(op.get("pending", True))
        state["train_end_enabled"] = True
    elif kind == "train_end_consumed":
        state["train_end_pending"] = False
        if op.get("task") is not None:
            state["todo"].append(list(op["task"]))
    elif kind == "stop_training":
        state["stop_training"] = True
        state["todo"] = [t for t in state["todo"] if t[3] != op.get("training_type", 0)]
    elif kind == "hint":
        if int(op["seq"]) > state["hint_seq"]:
            state["hint_seq"] = int(op["seq"])
            state["hint_target"] = int(op.get("target", 0))
            state["hint_reason"] = str(op.get("reason", ""))
    elif kind == "membership_epoch":
        state["membership_epoch"] = max(
            state["membership_epoch"], int(op["group_id"])
        )
    elif kind == "cooldown":
        state["cooldowns"][str(op["key"])] = float(op["ts"])
    else:
        # Forward compatibility: an op vocabulary grown by a newer master
        # must not brick an older replayer in tests; log and continue.
        logger.warning("journal: unknown op kind %r ignored", kind)


def replay(snapshot: Optional[Dict[str, Any]], ops: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Pure function: fold ops onto a snapshot (or empty state)."""
    state = empty_state()
    if snapshot:
        state.update(json.loads(json.dumps(snapshot)))  # deep copy via JSON
        # Tolerate snapshots from older vocabularies.
        for key, default in empty_state().items():
            state.setdefault(key, default)
    for op in ops:
        apply_op(state, op)
    return state


class MasterJournal:
    """Coordinator: append ops, auto-compact, and load replayed state.

    State providers register a zero-arg callable returning their slice of
    the snapshot dict; compaction merges all slices. `record()` is called
    from inside the providers' own locks (the dispatcher appends under its
    dispatch lock, BEFORE the RPC ack), so the append path takes only the
    Journal's internal file lock — and NEVER compacts inline: compaction
    calls back INTO the providers, so compacting from record() would
    self-deadlock on the caller's lock. Owners call maybe_compact() from
    a maintenance tick (the master's watchdog loop, the fleet master's
    aggregation loop) where no provider lock is held.
    """

    def __init__(self, directory: str, snapshot_every: Optional[int] = None,
                 durable: bool = True):
        self._journal = Journal(directory, durable=durable)
        self._snapshot_every = (
            knobs.get_int("ELASTICDL_JOURNAL_SNAPSHOT_EVERY")
            if snapshot_every is None
            else snapshot_every
        )
        self._ops_since_snapshot = 0
        self._providers: List[Callable[[], Dict[str, Any]]] = []
        self._lock = threading.Lock()
        self.directory = directory

    def add_state_provider(self, provider: Callable[[], Dict[str, Any]]) -> None:
        self._providers.append(provider)

    def load(self) -> Dict[str, Any]:
        snapshot, ops = self._journal.load()
        state = replay(snapshot, ops)
        with self._lock:
            self._ops_since_snapshot = len(ops)
        return state

    def record(self, op: Dict[str, Any]) -> None:
        self._journal.append(op)
        with self._lock:
            self._ops_since_snapshot += 1

    def compaction_due(self) -> bool:
        with self._lock:
            return (
                self._snapshot_every > 0
                and self._ops_since_snapshot >= self._snapshot_every
            )

    def maybe_compact(self) -> bool:
        """Compact when the WAL has outgrown snapshot_every ops. Call with
        no provider lock held (see class docstring). True when a snapshot
        was taken."""
        if not self.compaction_due():
            return False
        self.compact()
        return True

    def compact(self) -> None:
        """Gather provider slices into a fresh snapshot and truncate the WAL."""
        state: Dict[str, Any] = empty_state()
        for provider in self._providers:
            try:
                state.update(provider())
            except Exception:  # noqa: BLE001 - a bad provider must not lose the WAL
                logger.exception("journal: state provider failed; skipping compaction")
                return
        self._journal.snapshot(state)
        with self._lock:
            self._ops_since_snapshot = 0

    def close(self) -> None:
        self._journal.close()


def open_master_journal(directory: Optional[str] = None,
                        durable: bool = True) -> Optional[MasterJournal]:
    """Open the journal at the knob-configured (or given) dir; None if disabled."""
    directory = directory or knobs.get_str("ELASTICDL_MASTER_JOURNAL_DIR")
    if not directory:
        return None
    return MasterJournal(directory, durable=durable)
