"""Master-side metrics publishing: structured JSONL + TensorBoard events.

Reference counterpart: /root/reference/elasticdl/python/master/
tensorboard_service.py:21-62 (a tf.summary writer fed by the evaluation
service) — redesigned around a framework-neutral JSONL stream as the source
of truth (greppable, no reader dependency) with TensorBoard event files
written alongside when a SummaryWriter implementation is importable
(torch.utils.tensorboard in this image). The reference's k8s LoadBalancer
exposure (common/k8s_tensorboard_client.py:22-66) is subsumed by pointing
`tensorboard --logdir` at the job's metrics directory.
"""

import json
import os
import threading
import time

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("master.metrics_service")


def _make_summary_writer(log_dir):
    try:
        from torch.utils.tensorboard import SummaryWriter

        return SummaryWriter(log_dir=log_dir)
    except Exception:
        logger.info(
            "No TensorBoard SummaryWriter available; writing JSONL only"
        )
        return None


class MetricsService:
    """Append-only scalar metrics sink.

    Layout under `metrics_dir`:
      metrics.jsonl             one {"ts", "group", "step", <name>: value}
                                object per line
      events.out.tfevents.*     TensorBoard scalars (tag "<group>/<name>"),
                                when a writer is available
    """

    def __init__(self, metrics_dir, tensorboard=True):
        self._dir = metrics_dir
        os.makedirs(metrics_dir, exist_ok=True)
        self._path = os.path.join(metrics_dir, "metrics.jsonl")
        self._lock = threading.Lock()
        self._tb = _make_summary_writer(metrics_dir) if tensorboard else None

    def log_scalars(self, group, step, scalars):
        """scalars: {name: number}; step: model version / global step."""
        clean = {}
        for k, v in scalars.items():
            # A user metric named like a metadata field must not clobber
            # the record's ts/group/step.
            key = f"metric_{k}" if k in ("ts", "group", "step") else k
            clean[key] = float(v)
        line = json.dumps(
            {"ts": time.time(), "group": group, "step": int(step), **clean}
        )
        with self._lock:
            with open(self._path, "a") as f:
                f.write(line + "\n")
            if self._tb is not None:
                for name, value in clean.items():
                    self._tb.add_scalar(f"{group}/{name}", value, int(step))
                self._tb.flush()

    def on_evaluation_results(self, model_version, results):
        """EvaluationService.on_results hook."""
        self.log_scalars("eval", model_version, results)

    def close(self):
        if self._tb is not None:
            self._tb.close()
