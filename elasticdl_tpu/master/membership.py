"""Elastic membership epochs for the AllReduce path.

TPU-native replacement for the reference's Horovod rendezvous server
(/root/reference/elasticdl/python/master/rendezvous_server.py:31-110): the
master tracks the set of alive worker hosts; any change bumps `group_id`
(the rendezvous_id analog). Workers poll `get_comm_rank` between steps — a
changed group_id tells them to re-initialize the JAX distributed runtime
(jax.distributed) over the new host set and recompile their sharded step for
the new mesh, with the rank-0 worker broadcasting parameters. Ranks are
positions in the time-sorted host list, so they are stable for survivors.
"""

import threading
import time

from elasticdl_tpu.common.constants import (
    COORDINATOR_PORT_ROTATION as PORT_ROTATION,
)
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import emit_event
from elasticdl_tpu.observability.metrics import default_registry

logger = get_logger("master.membership")

_EPOCH = default_registry().gauge(
    "edl_membership_epoch", "Current AllReduce membership epoch"
)
_WORLD = default_registry().gauge(
    "edl_membership_world_size", "Workers in the current comm group"
)
# Epoch-bookkeeping cost (gauge updates + event emission, under the
# membership lock): at fleet churn rates this is per-event control-plane
# work the master must keep sub-millisecond.
_EPOCH_SECONDS = default_registry().histogram(
    "edl_master_membership_update_seconds",
    "Time spent on membership-epoch bookkeeping per epoch change",
    buckets=(
        0.00001, 0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05,
        0.1, 0.5, 1.0,
    ),
)


class MembershipManager:
    def __init__(self, coordinator_port=51000):
        self._lock = threading.RLock()
        self._hosts = []  # sorted by join order (pod start time analog)
        self._id_to_host = {}  # worker_id -> registered host
        self._group_id = 0
        self._coordinator_port = coordinator_port
        self._arrivals = {}  # epoch -> set of hosts at the join gate
        self._journal = None  # epoch bumps are journaled (PR 19)

    def attach_journal(self, journal):
        with self._lock:
            self._journal = journal

    def restore_state(self, state):
        """Resume the epoch counter past the journaled high-water mark so
        a relaunched master never re-issues an already-used group_id (the
        coordinator-port rotation and the arrive() gate both key on it)."""
        with self._lock:
            self._group_id = max(
                self._group_id, int(state.get("membership_epoch", 0))
            )
            _EPOCH.set(self._group_id)

    def export_state(self):
        with self._lock:
            return {"membership_epoch": self._group_id}

    def set_worker_hosts(self, hosts):
        """Replace the alive-host set (called by the instance manager on pod
        events, reference k8s_instance_manager.py:387-389). Bumps the group
        epoch iff membership changed."""
        with self._lock:
            if list(hosts) != self._hosts:
                self._hosts = list(hosts)
                self._group_id += 1
                self._epoch_changed_locked("replace")
                logger.info(
                    "Membership epoch %d: %d workers",
                    self._group_id,
                    len(self._hosts),
                )
            return self._group_id

    def _epoch_changed_locked(self, cause):
        t0 = time.perf_counter()
        if self._journal is not None:
            self._journal.record({
                "op": "membership_epoch",
                "group_id": self._group_id,
            })
        _EPOCH.set(self._group_id)
        _WORLD.set(len(self._hosts))
        emit_event(
            "membership_epoch",
            epoch=self._group_id,
            world=len(self._hosts),
            cause=cause,
        )
        _EPOCH_SECONDS.observe(time.perf_counter() - t0)

    def add_worker_host(self, host):
        with self._lock:
            if host not in self._hosts:
                self._hosts = self._hosts + [host]
                self._group_id += 1
                self._epoch_changed_locked("join")
                logger.info(
                    "Worker %s joined; membership epoch %d (%d workers)",
                    host,
                    self._group_id,
                    len(self._hosts),
                )
            return self._group_id

    def register(self, worker_id, host):
        """Join + remember worker_id -> host, so the instance manager can
        evict by id on failure (hosts alone are ambiguous: every local
        worker shares one IP and only differs in ephemeral port)."""
        with self._lock:
            old = self._id_to_host.get(worker_id)
            if old == host:
                return self._group_id
            self._id_to_host[worker_id] = host
        if old is not None:
            self.remove_worker_host(old)
        return self.add_worker_host(host)

    def remove_worker(self, worker_id):
        with self._lock:
            host = self._id_to_host.pop(worker_id, None)
        if host is not None:
            return self.remove_worker_host(host)
        return self.group_id

    def remove_worker_host(self, host):
        with self._lock:
            if host in self._hosts:
                self._hosts = [h for h in self._hosts if h != host]
                self._group_id += 1
                self._epoch_changed_locked("leave")
                logger.info(
                    "Worker %s left; membership epoch %d (%d workers)",
                    host,
                    self._group_id,
                    len(self._hosts),
                )
            return self._group_id

    def get_comm_rank(self, host):
        """(rank, world_size, group_id, coordinator_addr, coordinator_port).
        rank -1 means the host is not (yet) in the group — it should keep
        polling. coordinator_addr is the rank-0 worker's registered
        "ip:port" service address (state-broadcast pulls go there);
        coordinator_port is the fixed port for the jax.distributed
        coordination service on that same machine."""
        with self._lock:
            rank = self._hosts.index(host) if host in self._hosts else -1
            coordinator = self._hosts[0] if self._hosts else ""
            # Rotate the coordination-service port across epochs: the new
            # rank-0 process re-binds immediately after a teardown, and a
            # fixed port can linger in TIME_WAIT (or still be held by a
            # dying former coordinator). The rotation claims the block
            # [coordinator_port, coordinator_port + PORT_ROTATION - 1];
            # firewalls/NetworkPolicies must open the whole block, and
            # validate_args rejects a master_port inside it.
            port = self._coordinator_port + (self._group_id % PORT_ROTATION)
            return (
                rank,
                len(self._hosts),
                self._group_id,
                coordinator,
                port,
            )

    def arrive(self, host, epoch):
        """Two-phase join gate: record that `host` is about to enter the
        jax.distributed rendezvous for membership epoch `epoch`. Returns
        True once EVERY current member has arrived for the CURRENT epoch —
        the go signal that makes all members call initialize together,
        instead of each blocking at its own (possibly stale) epoch's
        rotated port until the coordination client's fatal deadline.
        Arrivals for superseded epochs are discarded (the caller re-polls
        get_comm_rank and re-arrives at the new epoch).

        A filled epoch's set deliberately persists until the epoch moves:
        every member polls until it OBSERVES ready=True, so clearing on
        first observation would deadlock the rest. The lone-rejoiner
        corner this leaves open (a worker restarting with a bitwise
        IDENTICAL host string inside an unchanged epoch gets an instant
        green light) is unreachable in practice — host strings embed the
        broadcast server's ephemeral port, so a restarted process always
        registers a new host and bumps the epoch — and if it ever did
        happen, that rendezvous can never complete anyway (survivors'
        ensure_world no-ops at an unchanged epoch), gate or no gate."""
        with self._lock:
            if epoch != self._group_id or host not in self._hosts:
                return False
            self._arrivals.setdefault(epoch, set()).add(host)
            # Prune superseded epochs' arrival sets.
            for stale in [e for e in self._arrivals if e != epoch]:
                del self._arrivals[stale]
            return self._arrivals[epoch] >= set(self._hosts)

    @property
    def group_id(self):
        with self._lock:
            return self._group_id

    @property
    def worker_hosts(self):
        with self._lock:
            return list(self._hosts)
