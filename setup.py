"""Packaging for elasticdl_tpu (reference bundles three pip packages;
this single package exposes the same CLI surface via the `edl` entrypoint).
"""

from setuptools import find_packages, setup

setup(
    name="elasticdl_tpu",
    version="0.1.0",
    description=(
        "Elastic, fault-tolerant distributed deep learning on TPUs "
        "(JAX/XLA) with dynamic data sharding"
    ),
    packages=find_packages(include=["elasticdl_tpu", "elasticdl_tpu.*"]),
    package_data={"elasticdl_tpu.proto": ["*.proto"],
                  "elasticdl_tpu.native": ["*.cc", "Makefile"]},
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "flax",
        "optax",
        "numpy",
        "grpcio",
        "protobuf",
        "ml_dtypes",
    ],
    extras_require={"k8s": ["kubernetes"]},
    entry_points={
        "console_scripts": ["edl=elasticdl_tpu.client.main:main"],
    },
)
